"""host-sync — implicit device->host syncs in hot-path modules.

The whole PR-1 pipeline story rests on one invariant: a faithful-mode
round pays exactly ONE explicit ``jax.device_get`` per dtype group (the
flatpack fetch) and nothing else crosses the device->host boundary.  An
accidental ``float(device_scalar)`` blocks the host on the in-flight
program and — on a remote-attached chip — costs a full tunnel round
trip per scalar (``tools/dispatch_cost_probe.py`` measured ~88 ms).

Flagged, in ``engine/``, ``ops/``, ``strategies/`` modules only:

- ``x.item()`` — the canonical per-scalar sync;
- ``float(x)`` / ``int(x)`` / ``bool(x)`` where ``x`` is device-tainted;
- ``np.asarray(x)`` / ``np.array(x)`` where ``x`` is device-tainted
  (implicit transfer; ``jax.device_get`` is the explicit spelling);
- ``jax.device_get(tree[field])`` — a per-field fetch: fetching members
  of one device tree in separate calls pays one transfer each; fetch
  the whole tree once (the flatpack discipline);
- ``print``/``print_rank``/``log_metric``/``logging`` of a
  device-tainted value — stringification forces the sync.

Device taint is tracked per function scope, seeded by:

- calls to ``jnp.*`` / ``jax.random.*`` / ``jax.lax.*`` / ``jax.nn.*``;
- calls through bindings created from ``jax.jit(...)`` /
  ``shard_map(...)`` / ``jax.pmap(...)`` / ``pl.pallas_call(...)``
  anywhere in the module — including ``self._fn = jax.jit(...)`` in one
  method called as ``self._fn(...)`` in another;
- subscripts/attributes of tainted values; tuple-unpacks of tainted
  calls taint every target.

``jax.device_get(...)`` results are host values and CLEAR taint, as
does rebinding a name to an untainted value.  Since flint v2 the taint
seeding is interprocedural: a name IMPORTED from another project module
where it is bound to a jit-factory result taints its call results here
too (``Project.imported_jit_names``).  VALUE flows across modules are
still the runtime strict mode's job (``MSRFLUTE_STRICT_TRANSFERS=1``,
docs/RUNBOOK.md).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (JIT_FACTORIES, Finding, ModuleInfo, Project,
                   call_name, dotted_name)

RULE = "host-sync"

#: call-name prefixes whose results live on device
_DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "jax.random.", "jax.lax.",
                    "jax.nn.", "optax.")
#: factories whose RESULT is a compiled callable (module-level tracking)
_JIT_FACTORIES = JIT_FACTORIES
_LOG_SINKS = {"print", "print_rank", "log_metric"}


def _collect_jitted_bindings(tree: ast.Module):
    """Names / ``self.<attr>``s bound to a jit-factory result anywhere in
    the module (method boundaries deliberately ignored: ``__init__``
    builds the callable, the round method calls it)."""
    names: Set[str] = set()
    attrs: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not (isinstance(value, ast.Call) and
                call_name(value) in _JIT_FACTORIES):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                attrs.add(tgt.attr)
    return names, attrs


class _ScopeTaint(ast.NodeVisitor):
    """One function scope's device-taint walk (statement order)."""

    def __init__(self, info: ModuleInfo, jit_names: Set[str],
                 jit_attrs: Set[str], findings: List[Finding]):
        self.info = info
        self.jit_names = jit_names
        self.jit_attrs = jit_attrs
        self.findings = findings
        self.tainted: Set[str] = set()
        #: per-field device_get candidates, flagged at scope end only if
        #: the scope fetches more than once (a lone string-key pick out
        #: of a host dict is one honest transfer)
        self.devget_count = 0
        self.devget_field_picks: List[Finding] = []

    # -- taint queries --------------------------------------------------
    def _is_jitted_callable(self, func: ast.AST) -> bool:
        name = dotted_name(func)
        if name is None:
            return False
        if name in self.jit_names:
            return True
        return name.startswith("self.") and \
            name.split(".", 1)[1] in self.jit_attrs

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = dotted_name(node)
            if name is None:
                return False
            # any tainted prefix taints the whole chain (state.params
            # when `state` is tainted)
            parts = name.split(".")
            return any(".".join(parts[:i]) in self.tainted
                       for i in range(1, len(parts) + 1))
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is None:
                return False
            if name in ("jax.device_get", "device_get"):
                return False  # explicit fetch: result is host memory
            if name.startswith(_DEVICE_PREFIXES):
                return True
            return self._is_jitted_callable(node.func)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        return False

    # -- assignments update taint ---------------------------------------
    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tainted)
            return
        name = dotted_name(target)
        if name is None:
            return
        if tainted:
            self.tainted.add(name)
        else:
            self.tainted.discard(name)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        tainted = self.is_tainted(node.value)
        for tgt in node.targets:
            self._bind(tgt, tainted)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if self.is_tainted(node.value):
            self._bind(node.target, True)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        self._bind(node.target, self.is_tainted(node.iter))
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested scopes get their own walk

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- the flags ------------------------------------------------------
    def _flag(self, node: ast.AST, message: str, hint: str) -> None:
        self.findings.append(Finding(RULE, self.info.path, node.lineno,
                                     message, hint))

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and not node.args:
            self._flag(node,
                       f"`{ast.unparse(node.func.value)}.item()` forces a "
                       "per-scalar device->host sync",
                       "batch the value into the packed-stats fetch "
                       "(utils/flatpack.py) or one explicit "
                       "jax.device_get of the whole tree")
        elif name in ("float", "int", "bool") and len(node.args) == 1 and \
                self.is_tainted(node.args[0]):
            self._flag(node,
                       f"`{name}({ast.unparse(node.args[0])})` blocks the "
                       "host on an in-flight device value",
                       "keep it on device, or fetch explicitly with "
                       "jax.device_get bundled with the round's other "
                       "host reads")
        elif name in ("np.asarray", "np.array", "numpy.asarray",
                      "numpy.array") and node.args and \
                self.is_tainted(node.args[0]):
            self._flag(node,
                       f"`{name}(...)` on a device value is an implicit "
                       "transfer",
                       "use jax.device_get (explicit, and visible to "
                       "jax.transfer_guard strict mode)")
        elif name in ("jax.device_get", "device_get"):
            self.devget_count += 1
            if node.args and isinstance(node.args[0], ast.Subscript) and \
                    isinstance(node.args[0].slice, ast.Constant) and \
                    isinstance(node.args[0].slice.value, str):
                # string-key subscript = picking ONE member out of a
                # stats dict (`stats["mag"]`); an array index
                # (`table[ids]`) is an on-device gather whose
                # device_get is one honest transfer
                self.devget_field_picks.append(Finding(
                    RULE, self.info.path, node.lineno,
                    f"per-field fetch "
                    f"`{name}({ast.unparse(node.args[0])})` pays one "
                    "transfer per member",
                    "device_get the whole tree once and index on host "
                    "(the flatpack single-transfer discipline)"))
        elif name in _LOG_SINKS or (name or "").startswith(
                ("logging.", "logger.", "_LOGGER.")):
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.FormattedValue):
                        val = sub.value
                    elif isinstance(sub, (ast.Name, ast.Attribute)) and \
                            sub is arg:
                        val = sub
                    else:
                        continue
                    if self.is_tainted(val):
                        self._flag(
                            node,
                            f"logging `{ast.unparse(val)}` stringifies a "
                            "device value (hidden sync)",
                            "jax.device_get it first (bundled with the "
                            "round's other host reads)")
                        break
        self.generic_visit(node)


def check(info: ModuleInfo,
          project: Optional[Project] = None) -> List[Finding]:
    if not info.is_hot_path:
        return []
    summary = project.modules.get(info.path) if project else None
    if summary is not None:
        # flint v2: the module summary already extracted the bindings,
        # and imported compiled callables (module-level
        # ``step = jax.jit(...)`` in another project file) seed taint
        # exactly like locally-built ones
        jit_names = set(summary.jit_names) | \
            project.imported_jit_names(info.path)
        jit_attrs = set(summary.jit_attrs)
    else:
        jit_names, jit_attrs = _collect_jitted_bindings(info.tree)
    findings: List[Finding] = []
    for node in ast.walk(info.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walker = _ScopeTaint(info, jit_names, jit_attrs, findings)
            for stmt in node.body:
                walker.visit(stmt)
            if walker.devget_count >= 2:
                findings.extend(walker.devget_field_picks)
    return findings
