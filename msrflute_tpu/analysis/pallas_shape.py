"""pallas-shape — TPU tile geometry + static-bound checks for kernels.

TPU vector memory is tiled (sublane x lane); a block whose trailing
dims do not align wastes VMEM and, for several op/dtype combos, fails
to lower at all (mosaic's misaligned-tile errors surface only on real
silicon — the exact class of chip-day surprise the queue discipline in
docs/RUNBOOK.md exists to avoid).  Minimum tiles by dtype:

    float32  (8, 128)      bfloat16 (16, 128)      int8/fp8 (32, 128)

Checked, in modules that import ``jax.experimental.pallas``:

- ``pl.BlockSpec`` shapes whose trailing dim is a resolvable int that
  is neither 1 (degenerate/scalar spec) nor a multiple of 128, and
  whose second-to-last resolvable int is neither 1 nor a multiple of 8
  (the f32 floor; bf16 kernels need 16 — the hint says so);
- ``pltpu.VMEM((..., ...), dtype)`` scratch shapes, same rule;
- Python ``for`` loops inside kernel bodies whose ``range()`` bound
  reads a *value* out of a Ref (``x_ref[...]``): trace-time unrollable
  only if the bound is static — a value-dependent bound cannot compile.
  (``ref.shape`` / grid constants are static and pass.)

Module-level int constants are folded (``_LANES = 128`` etc.), so the
common named-constant style is fully checked.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import (Finding, ModuleInfo, call_name, const_int,
                   module_int_constants)

RULE = "pallas-shape"

_LANE = 128
_SUBLANE_F32 = 8


def _imports_pallas(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if "pallas" in mod or any("pallas" in alias.name
                                      for alias in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("pallas" in alias.name for alias in node.names):
                return True
    return False


def _check_shape_tuple(node: ast.AST, consts, info: ModuleInfo,
                       findings: List[Finding], what: str) -> None:
    if not isinstance(node, ast.Tuple) or len(node.elts) < 2:
        return
    last = const_int(node.elts[-1], consts)
    second = const_int(node.elts[-2], consts)
    if last is not None and last != 1 and last % _LANE != 0:
        findings.append(Finding(
            RULE, info.path, node.lineno,
            f"{what} trailing dim {last} is not a multiple of the "
            f"{_LANE}-lane TPU tile",
            hint="pad the block's last dim to a multiple of 128 (mask "
                 "the tail in-kernel)"))
    if second is not None and second != 1 and second % _SUBLANE_F32 != 0:
        findings.append(Finding(
            RULE, info.path, node.lineno,
            f"{what} sublane dim {second} is not a multiple of "
            f"{_SUBLANE_F32}",
            hint="use a multiple of 8 for f32 (16 for bf16, 32 for "
                 "int8/fp8) so blocks land on whole tiles"))


def _kernel_functions(tree: ast.Module) -> Set[str]:
    """Functions passed (directly or via functools.partial) to
    ``pl.pallas_call``."""
    from .jit_purity import _named_function_args
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_name(node) in (
                "pl.pallas_call", "pallas_call"):
            out.update(_named_function_args(node))
    return out


def _ref_params(fn: ast.FunctionDef) -> Set[str]:
    """Kernel Ref args, by the ``*_ref`` naming convention plus 'every
    positional arg' as the conservative fallback when none match."""
    names = [a.arg for a in fn.args.args]
    refs = {n for n in names if n.endswith("_ref")}
    return refs or set(names)


def _reads_ref_value(node: ast.AST, refs: Set[str]) -> bool:
    """True if the expression subscripts a Ref (a VALUE read — dynamic
    at compile time), as opposed to touching only ``ref.shape``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript) and \
                isinstance(sub.value, ast.Name) and sub.value.id in refs:
            return True
    return False


def check(info: ModuleInfo) -> List[Finding]:
    if "pallas" not in info.src or not _imports_pallas(info.tree):
        return []
    consts = module_int_constants(info.tree)
    findings: List[Finding] = []

    for node in ast.walk(info.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in ("pl.BlockSpec", "BlockSpec") and node.args:
            _check_shape_tuple(node.args[0], consts, info, findings,
                               "BlockSpec block shape")
        elif name in ("pltpu.VMEM", "VMEM") and node.args:
            _check_shape_tuple(node.args[0], consts, info, findings,
                               "VMEM scratch shape")

    kernels = _kernel_functions(info.tree)
    if kernels:
        index = {}
        for node in ast.walk(info.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                index[node.name] = node
        for kname in sorted(kernels):
            fn = index.get(kname)
            if fn is None:
                continue
            refs = _ref_params(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.For) and \
                        isinstance(node.iter, ast.Call) and \
                        call_name(node.iter) == "range" and \
                        any(_reads_ref_value(a, refs)
                            for a in node.iter.args):
                    findings.append(Finding(
                        RULE, info.path, node.lineno,
                        f"kernel `{kname}` loops over a bound read from "
                        "a Ref — tracer-dependent Python loops cannot "
                        "compile",
                        hint="make the bound static (block shape / grid "
                             "constant) or use jax.lax.fori_loop with a "
                             "masked tail"))
    return findings
