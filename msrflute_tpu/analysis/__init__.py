"""fluteguard — TPU-safety static analysis for msrflute_tpu.

Six checkers, one CLI::

    python -m msrflute_tpu.analysis msrflute_tpu/     # or: tools/flint

- **host-sync**        implicit device->host syncs in hot-path modules
  (``engine/``, ``ops/``, ``strategies/``); the flatpack packed-stats
  fetch is the single sanctioned per-round transfer.
- **donation-aliasing** reads of a buffer after ``donate_argnums``
  handed it to a dispatch.
- **jit-purity**       side effects / host-state reads inside traced
  function bodies.
- **pallas-shape**     TPU tile alignment of kernel block shapes and
  tracer-dependent Python loop bounds.
- **put-loop**         per-leaf ``jax.device_put`` loops in hot-path
  modules; since PR 6 the dispatch inputs cross as one staged buffer
  per dtype group (``server_config.input_staging``).
- **schema-drift**     ``schema.py`` vs ``config.py`` vs docs
  cross-consistency.

Static findings pair with a runtime strict mode: under
``MSRFLUTE_STRICT_TRANSFERS=1`` the server round loop runs inside a
``jax.transfer_guard_device_to_host("disallow")`` scope
(``utils/strict.py``), so any implicit sync the linter's same-module
view cannot see raises at the offending line in e2e tests.

Suppression: ``# flint: disable=RULE reason`` (linted for staleness).
Baseline: ``analysis/baseline.json`` (shipped empty; the tier-1 gate
``tests/test_flint_clean.py`` fails on any non-baselined finding).
"""

from .core import (Finding, analyze, default_baseline_path,  # noqa: F401
                   filter_baseline, load_baseline, write_baseline)

RULES = ("host-sync", "donation-aliasing", "jit-purity", "pallas-shape",
         "put-loop", "schema-drift", "stale-suppression",
         "bare-suppression", "parse-error")
