"""fluteguard — TPU-safety static analysis for msrflute_tpu.

Nineteen checkers on one interprocedural engine, one CLI::

    python -m msrflute_tpu.analysis msrflute_tpu/     # or: tools/flint

Since flint v2 the checkers share a project-wide call graph with
per-function def-use summaries (``core.py``: :class:`~.core.Project`,
mtime-keyed summary caching), so rules reason ACROSS modules — a traced
body's helper in another file, a round path's fetch three calls deep.

- **host-sync**        implicit device->host syncs in hot-path modules
  (``engine/``, ``ops/``, ``strategies/``); the flatpack packed-stats
  fetch is the single sanctioned per-round transfer.  Taint seeding
  follows jitted bindings across modules.
- **donation-aliasing** reads of a buffer after ``donate_argnums``
  handed it to a dispatch.
- **jit-purity**       side effects / host-state reads inside traced
  function bodies (project-wide reachability: a helper imported into a
  traced body is checked in its own module).
- **pallas-shape**     TPU tile alignment of kernel block shapes and
  tracer-dependent Python loop bounds.
- **put-loop**         per-leaf ``jax.device_put`` loops in hot-path
  modules; since PR 6 the dispatch inputs cross as one staged buffer
  per dtype group (``server_config.input_staging``).
- **schema-drift**     ``schema.py`` vs ``config.py`` vs docs
  cross-consistency.
- **shard-ready**      cohort-axis host logic that would break under a
  mesh-sharded client axis (ROADMAP item 1 de-risking): host
  iteration/indexing over the leading client dim of device values,
  ``.shape[0]``-conditioned branches inside traced bodies.
- **recompile-hazard** the static counterpart of the PR 7 runtime
  recompile sentinel: data-derived values in static-arg positions,
  traced closures over mutable self-state, data-dependent operand
  shapes at jitted call sites.
- **transfer-budget**  the one-fetch-per-round invariant, proven on the
  call graph: explicit ``device_get`` sites reachable from each round
  root, flagged when a round-path function splits its fetch or fetches
  in a loop.
- **guard-matrix**     the host_orchestrated/robust/bucketing/secagg/
  fused-carry refusal matrix cross-checked against ``schema.py``
  bespoke checks and ``docs/config_extensions.md``.
- **event-schema**     telemetry event names and devbus publishers
  emitted by the code vs ``docs/observability.md``'s catalogue.
- **signal-safety**    nothing reachable from a ``signal.signal``
  handler may acquire a lock, do file IO, log or block (the PR 4
  telemetry-flush deadlock class); the deferred-flush pattern (work
  gated on a ``*_from_signal`` flag) is recognized as the blessed fix.
- **lock-discipline**  consistent lock acquisition order project-wide;
  no blocking call, file IO or ``device_get`` while holding a hot-path
  lock (Tracer, dataset cache, checkpoint condition); explicit
  acquire without release.
- **thread-escape**    mutable state handed across a thread boundary
  (``threading.Thread`` roots closed over the call graph) without a
  snapshot/copy — the PR 1 torn-snapshot class; anonymous ``Thread``
  spawns in hot paths flag too (telemetry attributes by thread name).
- **atomic-write**     durable artifacts (checkpoints, scorecard,
  baseline, status log) must use tmp + ``os.replace`` or hardlink
  rotation; bare ``open(path, "w")`` and bare ``os.rename`` of a
  committed slot flag, append-only JSONL streams stay silent.
- **mesh-axis**        collectives and ``P(...)`` specs in the modules
  that own the mesh must name the canonical axis constants
  (``CLIENTS_AXIS``/``MODEL_AXIS``); bare string axis literals flag.
- **shard-locality**   the vmapped/scanned per-lane body of a round
  program must be collective-free (closures from every vmap/scan
  root), and ``shard_map`` carry-table gathers must show block-local
  evidence (the ``axis_index`` conversion idiom, a ``mode="drop"``
  sentinel scatter, or shard-local bindings).
- **spec-drift**       the page pool's slot axis must shard over the
  clients mesh axis: replicated pool-spec bindings, replicated pool
  ``device_put``s (inline or through a named spec) and UNSHARDED pool
  puts in ``engine/`` flag (subsumes shard-ready's old
  replicated-pool check).
- **collective-budget** each round program's collective sites pinned
  both ways against docs/architecture.md's "Collective budget"
  paragraph — extra code sites flag with their round-root path, stale
  doc entries flag at the doc line.

Static findings pair with a runtime strict mode: under
``MSRFLUTE_STRICT_TRANSFERS=1`` the server round loop runs inside a
``jax.transfer_guard_device_to_host("disallow")`` scope
(``utils/strict.py``), so any implicit sync the linter's static view
cannot see raises at the offending line in e2e tests.

Suppression: ``# flint: disable=RULE reason`` (linted for staleness;
unknown rule names are errors, with rename hints from
``core.RULE_RENAMES``).  Baseline: ``analysis/baseline.json`` (shipped
empty; the tier-1 gate ``tests/test_flint_clean.py`` fails on any
non-baselined finding).
"""

from .core import (RULE_RENAMES, RULES, Finding, analyze,  # noqa: F401
                   default_baseline_path, filter_baseline, load_baseline,
                   write_baseline)
