"""event-schema — telemetry event contracts, code vs docs.

Structured events are the round loop's crash-forensics surface:
``tools/scope`` tabulates them per name, the RUNBOOK drills grep for
them, and operators alert on them.  An event the code emits but the
docs never mention is invisible operationally; an event the docs
advertise but nothing emits is an alert that can never fire.  Both
directions drift silently — this rule makes them mechanical:

- **emitted -> documented**: every literal event name reaching
  ``log_event(...)`` / ``emit_event(scope, ...)`` / ``*.event(...)`` /
  ``*.on_event(...)`` (f-string prefixes like ``f"watchdog_{kind}"``
  count as the family ``watchdog_*``), plus ``{"kind": "..."}`` event
  records built as dict literals (the xla.py drain-queue pattern), must
  appear in ``docs/observability.md``;
- **documented -> emitted**: every event token in the doc's
  "Instant events" catalogue must be emitted somewhere (globs match
  prefix families);
- **devbus publishers**: every ``devbus.publish("name", ...)`` /
  ``scope.devbus_host("name", ...)`` metric must appear in the doc
  (as `` `name` `` or `` `devbus/name` ``), and every name in the
  doc's "Built-in publishers" sentence must still be published.

Emission sites come from the module summaries (one AST walk shared
with the rest of flint v2); dynamic names (``ev.pop("kind")``) are
skipped — those records were emitted under their literal names at the
point the dict was BUILT, which the dict-literal scan covers.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from .core import (Finding, ModuleSummary, _iter_py_files,
                   build_project)

RULE = "event-schema"

#: paragraph anchors in docs/observability.md
DOC_EVENT_ANCHOR = "Instant events"
DOC_DEVBUS_ANCHOR = "Built-in publishers"

_BACKTICK_RE = re.compile(r"`([A-Za-z0-9_*/]+)`")
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*\*?$")


def _doc_anchor_tokens(doc_lines: List[str], anchor: str
                       ) -> List[Tuple[int, str]]:
    """Backticked event-shaped tokens in the paragraph starting at the
    line containing ``anchor`` (to the next blank line)."""
    out: List[Tuple[int, str]] = []
    for i, line in enumerate(doc_lines):
        if anchor not in line:
            continue
        for j in range(i, len(doc_lines)):
            if j > i and not doc_lines[j].strip():
                break
            for m in _BACKTICK_RE.finditer(doc_lines[j]):
                token = m.group(1)
                if _NAME_RE.match(token):
                    out.append((j + 1, token))
        break
    return out


def _name_matches(name: str, token: str) -> bool:
    """Glob-aware event-name match (either side may be a ``P*``
    prefix family)."""
    if name.endswith("*") and token.endswith("*"):
        return name[:-1].startswith(token[:-1]) or \
            token[:-1].startswith(name[:-1])
    if token.endswith("*"):
        return name.startswith(token[:-1])
    if name.endswith("*"):
        return token.startswith(name[:-1])
    return name == token


def _collect_modules(root: str) -> Dict[str, ModuleSummary]:
    pkg = os.path.join(root, "msrflute_tpu")
    files = _iter_py_files([pkg] if os.path.isdir(pkg) else [root])
    return build_project(root, files).modules


def check_project(root: str,
                  modules: Optional[Dict[str, ModuleSummary]] = None
                  ) -> List[Finding]:
    doc_path = os.path.join(root, "docs", "observability.md")
    if not os.path.exists(doc_path):
        return []  # not a tree this checker applies to
    rel_doc = os.path.relpath(doc_path, root).replace(os.sep, "/")
    with open(doc_path, "r", encoding="utf-8") as fh:
        doc_text = fh.read()
    doc_lines = doc_text.splitlines()
    doc_tokens = set(_BACKTICK_RE.findall(doc_text))

    if modules is None:
        modules = _collect_modules(root)
    else:
        # a subset run (`tools/flint engine/`) hands us only the
        # analyzed files' summaries; judging "documented event is
        # emitted nowhere" against a partial emission set would flood
        # with false positives — rescan the whole package instead
        pkg = os.path.join(root, "msrflute_tpu")
        if os.path.isdir(pkg):
            all_rel = {os.path.relpath(p, root).replace(os.sep, "/")
                       for p in _iter_py_files([pkg])}
            if not all_rel <= set(modules):
                modules = _collect_modules(root)

    findings: List[Finding] = []

    # ---- emitted -> documented ---------------------------------------
    emitted: List[Tuple[str, str, int]] = []   # (name, module, line)
    published: List[Tuple[str, str, int]] = []
    for path in sorted(modules):
        mod = modules[path]
        for name, line, _api in mod.events:
            emitted.append((name, path, line))
        for name, line, _api in mod.devbus:
            published.append((name, path, line))
    seen_names = set()
    for name, path, line in emitted:
        if name in seen_names:
            continue
        documented = any(_name_matches(name, tok) for tok in doc_tokens)
        if not documented:
            seen_names.add(name)
            findings.append(Finding(
                RULE, path, line,
                f"telemetry event `{name}` is emitted but "
                "docs/observability.md never mentions it",
                hint="add it to the 'Instant events' catalogue — "
                     "undocumented events are invisible to operators "
                     "and tools/scope readers"))
    seen_pub = set()
    for name, path, line in published:
        if name in seen_pub:
            continue
        core_name = name.rstrip("*")
        if not (name in doc_tokens or f"devbus/{core_name}" in doc_tokens
                or any(_name_matches(name, tok) for tok in doc_tokens)):
            seen_pub.add(name)
            findings.append(Finding(
                RULE, path, line,
                f"devbus metric `{name}` is published but "
                "docs/observability.md never mentions it",
                hint="add it to the 'Built-in publishers' list (the "
                     "devbus section)"))

    # ---- documented -> emitted ---------------------------------------
    emitted_names = {name for name, _, _ in emitted}
    for line_no, token in _doc_anchor_tokens(doc_lines,
                                             DOC_EVENT_ANCHOR):
        if not any(_name_matches(name, token) for name in emitted_names):
            findings.append(Finding(
                RULE, rel_doc, line_no,
                f"documented event `{token}` is emitted nowhere",
                hint="the emission was renamed or dropped — fix the "
                     "doc or restore the event (an advertised event "
                     "that can never fire breaks alerting)"))
    published_names = {name for name, _, _ in published}
    for line_no, token in _doc_anchor_tokens(doc_lines,
                                             DOC_DEVBUS_ANCHOR):
        if not any(_name_matches(name, token)
                   for name in published_names):
            findings.append(Finding(
                RULE, rel_doc, line_no,
                f"documented devbus publisher `{token}` publishes "
                "nowhere",
                hint="the publisher was renamed or dropped — fix the "
                     "doc or restore the publish call"))
    return findings
