"""collective-budget — each round program's collective sites, pinned.

The transfer budget bounds device->host traffic; this rule bounds the
CROSS-SHARD traffic inside the programs themselves.  Every collective
site in the round path is a deliberate piece of the layout: the
finalize psum, the metrics all_gather, the axis_index slot conversion
— and each one was costed when the mesh plane was designed.  A new
``psum`` slipped into a refactor is invisible at review (it traces,
it compiles, it is bit-correct on one device) but multiplies per-round
latency by the mesh's slowest link.  So the budget is written down and
machine-checked BOTH ways against ``docs/architecture.md``'s
"Collective budget" paragraph:

- **code -> doc**: an ``engine/`` module with more sites of an op than
  the doc grants gets each extra site flagged (with the round-root
  path when the function is on one, transfer-budget style).  A
  deliberate new site takes a reasoned inline pragma AND a doc bump —
  the paragraph is the costing record;
- **doc -> code**: a documented entry the code no longer matches (op
  dropped, count shrank, module gone) flags at the doc line — a stale
  budget is how the NEXT extra collective hides.

Doc format, one module per line in the paragraph anchored by
"Collective budget" (scanned to the next blank line, event-schema
style)::

    - `engine/round.py`: `psum` x2, `all_gather` x2, `axis_index` x2

Scope: ``engine/`` modules (``ops/`` kernels are axis-parameterized
library code — their budgets belong to whichever program instantiates
them).  ``axis_index`` counts like a collective here: it is cheap, but
its COUNT pins the global->local conversion idiom shard-locality
relies on.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from .core import Finding, ModuleSummary, Project, _iter_py_files, \
    build_project
from .transfer_budget import BOUNDARY_RE, PAGER_ROOT_RE, ROUND_ROOT_RE

RULE = "collective-budget"

#: paragraph anchor in docs/architecture.md
DOC_ANCHOR = "Collective budget"

_MOD_RE = re.compile(r"`((?:[\w\-]+/)+[\w\-]+\.py)`")
_OP_RE = re.compile(r"`(\w+)`\s*x(\d+)")


def _doc_budget(doc_lines: List[str]
                ) -> Dict[str, Tuple[int, Dict[str, int]]]:
    """``{module: (doc line, {op: count})}`` from the anchored
    paragraph (anchor line to the next blank)."""
    out: Dict[str, Tuple[int, Dict[str, int]]] = {}
    for i, line in enumerate(doc_lines):
        if DOC_ANCHOR not in line:
            continue
        # scan past the anchor paragraph's own blank separator; stop at
        # the first blank AFTER at least one module entry was read
        for j in range(i, len(doc_lines)):
            if out and j > i and not doc_lines[j].strip():
                break
            mod = _MOD_RE.search(doc_lines[j])
            if not mod:
                continue
            ops = {op: int(n) for op, n in
                   _OP_RE.findall(doc_lines[j][mod.end():])}
            if ops:
                out[mod.group(1)] = (j + 1, ops)
        break
    return out


def _doc_key(path: str) -> str:
    """Doc entries name modules package-relative (``engine/round.py``);
    project summaries key root-relative (``msrflute_tpu/engine/...``)."""
    head, _, tail = path.partition("/")
    return tail if head == "msrflute_tpu" and tail else path


def _collect_modules(root: str) -> Dict[str, ModuleSummary]:
    pkg = os.path.join(root, "msrflute_tpu")
    files = _iter_py_files([pkg] if os.path.isdir(pkg) else [root])
    return build_project(root, files).modules


def check_project(root: str,
                  project: Optional[Project] = None) -> List[Finding]:
    doc_path = os.path.join(root, "docs", "architecture.md")
    if not os.path.exists(doc_path):
        return []  # not a tree this checker applies to
    rel_doc = os.path.relpath(doc_path, root).replace(os.sep, "/")
    with open(doc_path, "r", encoding="utf-8") as fh:
        doc_lines = fh.read().splitlines()
    budget = _doc_budget(doc_lines)

    modules = project.modules if project is not None else None
    # a subset run (`tools/flint engine/round.py`) would judge the doc
    # against a partial site census — rescan the package instead
    pkg = os.path.join(root, "msrflute_tpu")
    if os.path.isdir(pkg):
        all_rel = {os.path.relpath(p, root).replace(os.sep, "/")
                   for p in _iter_py_files([pkg])}
        if modules is None or not all_rel <= set(modules):
            modules = _collect_modules(root)
    if modules is None:
        return []

    # round-root closure for transfer-budget-style path reporting
    roots = []
    for path, mod in modules.items():
        if "engine" not in path.split("/"):
            continue
        for qual, fn in mod.functions.items():
            if (ROUND_ROOT_RE.search(fn.name) or
                    PAGER_ROOT_RE.match(fn.name)) and \
                    not BOUNDARY_RE.search(fn.name):
                roots.append((path, qual))
    graph = project if project is not None \
        else Project(os.path.abspath(root), modules)
    parents = graph.reachable_from(sorted(roots), stop=BOUNDARY_RE) \
        if roots else {}

    findings: List[Finding] = []
    seen_mods = set()
    for path in sorted(modules):
        if "engine" not in path.split("/"):
            continue
        mod = modules[path]
        # (op, line, fn qual) sites, module-wide
        sites: Dict[str, List[Tuple[int, str]]] = {}
        for qual, fn in sorted(mod.functions.items()):
            for op, line, _axis in fn.collectives:
                sites.setdefault(op, []).append((line, qual))
        if not sites and _doc_key(path) not in budget:
            continue
        seen_mods.add(_doc_key(path))
        doc_line, doc_ops = budget.get(_doc_key(path), (0, {}))
        # ---- code -> doc: extra sites flag --------------------------
        for op in sorted(sites):
            allowed = doc_ops.get(op, 0)
            extra = sorted(sites[op])[allowed:]
            for line, qual in extra:
                key = (path, qual)
                via = ""
                if key in parents:
                    chain = graph.call_path(parents, key)
                    if len(chain) > 1:
                        via = f" (round path: {' -> '.join(chain)})"
                findings.append(Finding(
                    RULE, path, line,
                    f"collective site `{op}` in `{qual}` exceeds the "
                    f"documented budget ({allowed} x `{op}` for "
                    f"{path} in docs/architecture.md)" + via,
                    hint="a new cross-shard collective multiplies "
                         "per-round latency by the mesh's slowest "
                         "link: if deliberate, add a reasoned "
                         "`# flint: disable=collective-budget` pragma "
                         "AND bump the doc's Collective budget line "
                         "(the costing record); otherwise hoist it to "
                         "an existing sanctioned site"))
        # ---- doc -> code: stale budget flags ------------------------
        for op, count in sorted(doc_ops.items()):
            have = len(sites.get(op, []))
            if have < count:
                findings.append(Finding(
                    RULE, rel_doc, doc_line,
                    f"docs/architecture.md budgets {count} x `{op}` "
                    f"for {path} but the code has {have}",
                    hint="the site moved or was removed — shrink the "
                         "budget line to match (a stale budget grants "
                         "headroom the next stray collective hides "
                         "in)"))
    for path, (doc_line, _ops) in sorted(budget.items()):
        if path not in seen_mods:
            findings.append(Finding(
                RULE, rel_doc, doc_line,
                f"docs/architecture.md budgets collectives for "
                f"`{path}`, which has none (or does not exist)",
                hint="drop the stale budget entry or fix the module "
                     "path"))
    return findings
