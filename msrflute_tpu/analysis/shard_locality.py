"""shard-locality — collectives live at the shard boundary, not in
lanes; shard_map gathers are block-local.

PR 15/16's layout contract, until now a comment: the vmapped/scanned
PER-CLIENT (per-lane) body of a round program never communicates —
every cross-client reduction happens once, in the finalize/combine
region at the top of the ``shard_map`` body.  A ``psum`` inside the
lane body runs per lane step (K collectives per round instead of one)
and, worse, couples lanes that the megabatch tape planner proved
independent.  And inside a ``shard_map`` body, a carry-table gather
must index by BLOCK-LOCAL slot ids: the engine converts global slot
ids with the ``axis_index`` idiom (``off = axis_index(CLIENTS_AXIS) *
shard_slots; slots - off``) — a gather by raw global ids reads out of
bounds on every shard but 0 (clipped: silently wrong rows; the exact
pre-PR-15 replicated-pool shape).

Two checks, both on the project call graph:

1. **lane collectives** — from every vmap/scan root
   (``ModuleSummary.lane_roots``) in ``engine//strategies/``, the call
   closure must contain NO collective (``axis_index`` excluded — it is
   the conversion idiom, not communication).  Each violation names the
   lane-root path, transfer-budget style.
2. **shard_map gather locality** — from every ``shard_map`` root in
   ``engine/``, a closure containing pool-table gathers
   (``slot_gathers``) must carry shard-local evidence: an
   ``axis_index`` call (the global->local conversion), a
   ``mode="drop"`` sentinel scatter (the fixed-shape page-in), or a
   ``shard_slots``/local-ids marker in the body's or its BUILDER
   function's bindings (``hi = self.shard_slots if ...`` — the paging
   gather clamp).  A gather with none of these is indexing the pool by
   global ids.

GSPMD-mode dispatch (no ``shard_map``; the partitioner places the
collectives) never registers roots here and is unjudged — the runtime
equivalence suite (``tests/test_fleet_mesh.py``) owns that mode.
"""

from __future__ import annotations

import re
from typing import List, Optional, Set, Tuple

from .core import Finding, Project

RULE = "shard-locality"

#: lane roots are judged where the round programs live
_SCOPE_PARTS = ("engine", "strategies")
#: shard_map gather audit: the carry/paging plumbing is engine-only
_SHARDMAP_PARTS = ("engine",)

#: bindings/attribute reads that mark a shard_map body (or its builder)
#: as reasoning in BLOCK-LOCAL slot coordinates
_SHARD_LOCAL_RE = re.compile(
    r"(shard_slots|shard_local|local_ids|local_slots)")


def _has_part(path: str, parts: Tuple[str, ...]) -> bool:
    segs = path.split("/")
    return any(p in segs for p in parts)


def _resolve_root(project: Project, path: str, ref: str,
                  cls: Optional[str], builder_qual: str):
    """A nested body handed to vmap/scan/shard_map resolves in its
    BUILDER's scope first — round.py defines one ``shard_body`` per
    builder method, and the module-wide last-def name index would
    conflate them all onto the final definition."""
    if builder_qual and "." not in ref:
        nested = builder_qual + "." + ref
        mod = project.modules.get(path)
        if mod is not None and nested in mod.functions:
            return (path, nested)
    return project.resolve(path, ref, cls)


def check_project(project: Project,
                  emit_paths: Optional[Set[str]] = None
                  ) -> List[Finding]:
    findings: List[Finding] = []

    # ---- 1. lane closures are collective-free ------------------------
    lane_roots = []
    for path, mod in project.modules.items():
        if not _has_part(path, _SCOPE_PARTS):
            continue
        for ref, cls, builder_qual in mod.lane_roots:
            resolved = _resolve_root(project, path, ref, cls,
                                     builder_qual)
            if resolved:
                lane_roots.append(resolved)
    if lane_roots:
        parents = project.reachable_from(sorted(set(lane_roots)))
        for key in sorted(parents):
            fn = project.function(key)
            if fn is None or not _has_part(fn.module, _SCOPE_PARTS):
                continue
            if emit_paths is not None and fn.module not in emit_paths:
                continue
            chain = project.call_path(parents, key)
            via = f" (lane path: {' -> '.join(chain)})" \
                if len(chain) > 1 else ""
            for op, line, _axis in fn.collectives:
                if op == "axis_index":
                    continue
                findings.append(Finding(
                    RULE, fn.module, line,
                    f"collective `{op}` inside the vmapped/scanned "
                    f"per-lane body `{fn.qual}` — one collective PER "
                    "LANE STEP instead of one per round" + via,
                    hint="hoist the reduction to the finalize/combine "
                         "region of the shard_map body (the sanctioned "
                         "collective site); lane bodies must stay "
                         "communication-free so the tape planner's "
                         "independence proof holds"))

    # ---- 2. shard_map gathers are block-local ------------------------
    for path, mod in sorted(project.modules.items()):
        if not _has_part(path, _SHARDMAP_PARTS):
            continue
        for ref, cls, builder_qual, _line in mod.shardmap_roots:
            resolved = _resolve_root(project, path, ref, cls,
                                     builder_qual)
            if resolved is None:
                continue
            parents = project.reachable_from([resolved])
            gathers = []
            evidence = False
            for key in parents:
                fn = project.function(key)
                if fn is None:
                    continue
                gathers.extend((fn, g) for g in fn.slot_gathers)
                if fn.drop_scatters or any(
                        op == "axis_index"
                        for op, _l, _a in fn.collectives):
                    evidence = True
                blob = " ".join(fn.local_assigns) + " " + \
                    " ".join(fn.local_assigns.values()) + " " + \
                    " ".join(fn.self_reads)
                if _SHARD_LOCAL_RE.search(blob):
                    evidence = True
            builder = mod.functions.get(builder_qual)
            if builder is not None and not evidence:
                blob = " ".join(builder.local_assigns) + " " + \
                    " ".join(builder.local_assigns.values()) + " " + \
                    " ".join(builder.self_reads)
                if _SHARD_LOCAL_RE.search(blob):
                    evidence = True
            if evidence:
                continue
            for fn, (base, slice_src, line) in gathers:
                if emit_paths is not None and \
                        fn.module not in emit_paths:
                    continue
                findings.append(Finding(
                    RULE, fn.module, line,
                    f"carry-table gather `{base}[{slice_src}]` inside "
                    f"shard_map body `{fn.qual}` indexes by GLOBAL "
                    "slot ids — out of bounds (clipped: wrong rows) on "
                    "every shard but 0",
                    hint="convert to block-local ids first (`off = "
                         "axis_index(CLIENTS_AXIS) * shard_slots; "
                         "slots - off`) or gather through the pager's "
                         "shard_slots-clamped path — the slot a lane "
                         "uses lives on the lane's own shard"))
    return findings
