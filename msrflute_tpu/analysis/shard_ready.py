"""shard-ready — cohort-axis host logic that breaks under `shard_map`.

ROADMAP item 1 shards the cohort (client) axis of the round program
across a device mesh.  Everything that is *sharding-oblivious* — vmap
over the leading axis, psum'd reductions, masked static-shape math —
survives that move untouched.  What does NOT survive is host Python
that reasons about the leading client dimension of a DEVICE value:

- ``for c in device_value:`` — host iteration over the leading axis
  materializes one element per step (a transfer each) and sees only the
  LOCAL shard once the axis is sharded;
- ``device_value[i]`` inside a host loop over ``range(...)`` — the same
  per-client indexing spelled with an index variable;
- ``if x.shape[0] ...`` / ``while x.shape[0] ...`` inside a TRACED body
  — a cohort-geometry branch: under ``shard_map`` the traced leading
  dim is the per-shard K, not the global cohort, so the branch silently
  changes meaning (and each distinct K compiles its own side).

Scope: ``engine/`` and ``strategies/`` modules — the code that owns the
cohort axis.  Device taint reuses the host-sync tracker (jnp/jax.random
results, jitted-binding results incl. cross-module imports); host
values fetched through ``jax.device_get`` are clear, so the ubiquitous
"loop over fetched numpy stats" pattern never flags.

Traced-body detection comes from the project call graph
(``Project.traced_reachable()``), so a branch helper called from a
traced body in ANOTHER module is still judged traced.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from .core import (Finding, ModuleInfo, Project, call_name,
                   function_nodes)
from .host_sync import _collect_jitted_bindings, _ScopeTaint

RULE = "shard-ready"

_SCOPE_PARTS = ("engine", "strategies")


def _in_scope(info: ModuleInfo) -> bool:
    parts = info.path.split("/")
    return any(p in parts for p in _SCOPE_PARTS)


class _ShardWalk(_ScopeTaint):
    """Taint-aware walk flagging host iteration/indexing over device
    values.  Inherits the host-sync taint rules but emits none of its
    findings (they are host-sync's business)."""

    def __init__(self, info: ModuleInfo, jit_names, jit_attrs,
                 findings: List[Finding]):
        super().__init__(info, jit_names, jit_attrs, [])
        self.out = findings
        self.range_vars: List[str] = []

    # host-sync's flags are suppressed; only taint propagation remains
    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        tainted_iter = self.is_tainted(node.iter)
        if tainted_iter:
            self.out.append(Finding(
                RULE, self.info.path, node.lineno,
                f"host iteration over device value "
                f"`{ast.unparse(node.iter)}` walks the leading (client) "
                "axis on the host",
                hint="this pays a transfer per element today and sees "
                     "only the local shard under a mesh-sharded client "
                     "axis — vmap/scan over the axis on device, or "
                     "jax.device_get the whole array first"))
        self._bind(node.target, tainted_iter)
        is_range = isinstance(node.iter, ast.Call) and \
            call_name(node.iter) == "range"
        var = node.target.id if isinstance(node.target, ast.Name) else None
        if is_range and var:
            self.range_vars.append(var)
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        if is_range and var:
            self.range_vars.pop()

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load) and \
                isinstance(node.slice, ast.Name) and \
                node.slice.id in self.range_vars and \
                self.is_tainted(node.value):
            self.out.append(Finding(
                RULE, self.info.path, node.lineno,
                f"host per-client indexing "
                f"`{ast.unparse(node)}` into a device value inside a "
                "loop",
                hint="a device gather (`x[ids]`) or vmap keeps the "
                     "cohort axis on device; host indexing pays a "
                     "transfer per client and breaks when the axis is "
                     "sharded"))
        self.generic_visit(node)


def _check_traced_branches(info: ModuleInfo, traced_quals: Set[str],
                           findings: List[Finding]) -> None:
    """``.shape[0]``-conditioned if/while tests inside traced bodies."""
    nodes = function_nodes(info)
    for qual in sorted(traced_quals):
        fn = nodes.get(qual)
        if fn is None:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Subscript) and \
                        isinstance(sub.value, ast.Attribute) and \
                        sub.value.attr == "shape" and \
                        isinstance(sub.slice, ast.Constant) and \
                        sub.slice.value == 0:
                    findings.append(Finding(
                        RULE, info.path, node.lineno,
                        f"traced `{fn.name}` branches on "
                        f"`{ast.unparse(sub)}` — under a mesh-sharded "
                        "client axis the traced leading dim is the "
                        "per-shard count, not the cohort",
                        hint="make the behavior a data operand (mask / "
                             "capacity scalar) instead of trace-time "
                             "cohort geometry"))
                    break


#: identifier tokens that mark a SLOT-AXIS table (the fleet page pool,
#: carry-row buffers): a replicated NamedSharding on one of these in an
#: engine/ hot path is the replicated-pool bug class — page-in bytes,
#: writeback fetches, and pool HBM all multiply by mesh size instead of
#: dividing (``parallel.sharding.slot_pool_sharding`` is the fix)
_POOL_TOKENS = frozenset({"row", "rows", "pool", "slot", "slots",
                          "table", "tables"})
_TOKEN_SPLIT = re.compile(r"[^a-zA-Z0-9]+")


def _pool_name(name: Optional[str]) -> bool:
    if not name:
        return False
    return any(tok in _POOL_TOKENS
               for tok in _TOKEN_SPLIT.split(name.lower()))


def _name_of(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_replicated_spec_call(node: ast.AST) -> bool:
    """``NamedSharding(mesh, P())`` — a replicated spec construction
    (``P()``/``PartitionSpec()`` with no axis arguments)."""
    if not isinstance(node, ast.Call) or \
            (call_name(node) or "").split(".")[-1] != "NamedSharding" or \
            len(node.args) < 2:
        return False
    spec = node.args[1]
    return isinstance(spec, ast.Call) and \
        (call_name(spec) or "").split(".")[-1] in ("P", "PartitionSpec") \
        and not spec.args and not spec.keywords


def _check_replicated_pool(info: ModuleInfo,
                           findings: List[Finding]) -> None:
    """Replicated slot-axis tables in engine/ hot paths: a
    ``NamedSharding(mesh, P())`` bound to (or device_put onto) a
    pool/rows/slots/table value makes every device carry — and every
    page-in/writeback move — the WHOLE pool instead of its shard.  The
    sharded spec (``slot_pool_sharding`` / ``P(CLIENTS_AXIS)``) stays
    silent."""
    if "engine" not in info.path.split("/"):
        return
    replicated_names: Set[str] = set()
    for node in ast.walk(info.tree):
        if isinstance(node, ast.Assign) and \
                _is_replicated_spec_call(node.value):
            for tgt in node.targets:
                name = _name_of(tgt)
                if name:
                    replicated_names.add(name)
                if name and _pool_name(name):
                    findings.append(Finding(
                        RULE, info.path, node.lineno,
                        f"slot-axis table spec `{name}` is a REPLICATED "
                        "NamedSharding — the page pool's slot axis must "
                        "shard over the clients mesh axis",
                        hint="use parallel.sharding.slot_pool_sharding "
                             "(P(CLIENTS_AXIS) on axis 0): per-device "
                             "pool HBM and page-in/writeback bytes "
                             "become total/mesh_size instead of "
                             "xmesh_size"))
    for node in ast.walk(info.tree):
        if not isinstance(node, ast.Call) or \
                (call_name(node) or "").split(".")[-1] != "device_put" \
                or len(node.args) < 2:
            continue
        target_name = _name_of(node.args[0])
        if not _pool_name(target_name):
            continue
        spec = node.args[1]
        if _is_replicated_spec_call(spec) or \
                _name_of(spec) in replicated_names:
            findings.append(Finding(
                RULE, info.path, node.lineno,
                f"device_put of slot-axis table `{target_name}` with a "
                "replicated sharding — every device receives the whole "
                "pool buffer (bytes x mesh_size)",
                hint="stage pool rows with slot_pool_sharding "
                     "(P(CLIENTS_AXIS)): each device then receives "
                     "only its shard's segment, total/mesh_size bytes"))


def check(info: ModuleInfo,
          project: Optional[Project] = None) -> List[Finding]:
    if not _in_scope(info):
        return []
    findings: List[Finding] = []
    summary = project.modules.get(info.path) if project else None
    if summary is not None:
        jit_names = set(summary.jit_names) | \
            project.imported_jit_names(info.path)
        jit_attrs = set(summary.jit_attrs)
    else:
        jit_names, jit_attrs = _collect_jitted_bindings(info.tree)
    traced_quals: Set[str] = set()
    if project is not None:
        traced_quals = {q for (m, q) in project.traced_reachable()
                        if m == info.path}
    nodes = function_nodes(info)
    for qual, fn_node in sorted(nodes.items()):
        if qual in traced_quals:
            continue  # traced bodies: geometry rules below, not taint
        walker = _ShardWalk(info, jit_names, jit_attrs, findings)
        for stmt in fn_node.body:
            walker.visit(stmt)
    _check_traced_branches(info, traced_quals, findings)
    _check_replicated_pool(info, findings)
    return findings
