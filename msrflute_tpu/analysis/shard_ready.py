"""shard-ready — cohort-axis host logic that breaks under `shard_map`.

ROADMAP item 1 shards the cohort (client) axis of the round program
across a device mesh.  Everything that is *sharding-oblivious* — vmap
over the leading axis, psum'd reductions, masked static-shape math —
survives that move untouched.  What does NOT survive is host Python
that reasons about the leading client dimension of a DEVICE value:

- ``for c in device_value:`` — host iteration over the leading axis
  materializes one element per step (a transfer each) and sees only the
  LOCAL shard once the axis is sharded;
- ``device_value[i]`` inside a host loop over ``range(...)`` — the same
  per-client indexing spelled with an index variable;
- ``if x.shape[0] ...`` / ``while x.shape[0] ...`` inside a TRACED body
  — a cohort-geometry branch: under ``shard_map`` the traced leading
  dim is the per-shard K, not the global cohort, so the branch silently
  changes meaning (and each distinct K compiles its own side).

Scope: ``engine/`` and ``strategies/`` modules — the code that owns the
cohort axis.  Device taint reuses the host-sync tracker (jnp/jax.random
results, jitted-binding results incl. cross-module imports); host
values fetched through ``jax.device_get`` are clear, so the ubiquitous
"loop over fetched numpy stats" pattern never flags.

Traced-body detection comes from the project call graph
(``Project.traced_reachable()``), so a branch helper called from a
traced body in ANOTHER module is still judged traced.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import (Finding, ModuleInfo, Project, call_name,
                   function_nodes)
from .host_sync import _collect_jitted_bindings, _ScopeTaint

RULE = "shard-ready"

_SCOPE_PARTS = ("engine", "strategies")


def _in_scope(info: ModuleInfo) -> bool:
    parts = info.path.split("/")
    return any(p in parts for p in _SCOPE_PARTS)


class _ShardWalk(_ScopeTaint):
    """Taint-aware walk flagging host iteration/indexing over device
    values.  Inherits the host-sync taint rules but emits none of its
    findings (they are host-sync's business)."""

    def __init__(self, info: ModuleInfo, jit_names, jit_attrs,
                 findings: List[Finding]):
        super().__init__(info, jit_names, jit_attrs, [])
        self.out = findings
        self.range_vars: List[str] = []

    # host-sync's flags are suppressed; only taint propagation remains
    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        tainted_iter = self.is_tainted(node.iter)
        if tainted_iter:
            self.out.append(Finding(
                RULE, self.info.path, node.lineno,
                f"host iteration over device value "
                f"`{ast.unparse(node.iter)}` walks the leading (client) "
                "axis on the host",
                hint="this pays a transfer per element today and sees "
                     "only the local shard under a mesh-sharded client "
                     "axis — vmap/scan over the axis on device, or "
                     "jax.device_get the whole array first"))
        self._bind(node.target, tainted_iter)
        is_range = isinstance(node.iter, ast.Call) and \
            call_name(node.iter) == "range"
        var = node.target.id if isinstance(node.target, ast.Name) else None
        if is_range and var:
            self.range_vars.append(var)
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        if is_range and var:
            self.range_vars.pop()

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load) and \
                isinstance(node.slice, ast.Name) and \
                node.slice.id in self.range_vars and \
                self.is_tainted(node.value):
            self.out.append(Finding(
                RULE, self.info.path, node.lineno,
                f"host per-client indexing "
                f"`{ast.unparse(node)}` into a device value inside a "
                "loop",
                hint="a device gather (`x[ids]`) or vmap keeps the "
                     "cohort axis on device; host indexing pays a "
                     "transfer per client and breaks when the axis is "
                     "sharded"))
        self.generic_visit(node)


def _check_traced_branches(info: ModuleInfo, traced_quals: Set[str],
                           findings: List[Finding]) -> None:
    """``.shape[0]``-conditioned if/while tests inside traced bodies."""
    nodes = function_nodes(info)
    for qual in sorted(traced_quals):
        fn = nodes.get(qual)
        if fn is None:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Subscript) and \
                        isinstance(sub.value, ast.Attribute) and \
                        sub.value.attr == "shape" and \
                        isinstance(sub.slice, ast.Constant) and \
                        sub.slice.value == 0:
                    findings.append(Finding(
                        RULE, info.path, node.lineno,
                        f"traced `{fn.name}` branches on "
                        f"`{ast.unparse(sub)}` — under a mesh-sharded "
                        "client axis the traced leading dim is the "
                        "per-shard count, not the cohort",
                        hint="make the behavior a data operand (mask / "
                             "capacity scalar) instead of trace-time "
                             "cohort geometry"))
                    break


def check(info: ModuleInfo,
          project: Optional[Project] = None) -> List[Finding]:
    if not _in_scope(info):
        return []
    findings: List[Finding] = []
    summary = project.modules.get(info.path) if project else None
    if summary is not None:
        jit_names = set(summary.jit_names) | \
            project.imported_jit_names(info.path)
        jit_attrs = set(summary.jit_attrs)
    else:
        jit_names, jit_attrs = _collect_jitted_bindings(info.tree)
    traced_quals: Set[str] = set()
    if project is not None:
        traced_quals = {q for (m, q) in project.traced_reachable()
                        if m == info.path}
    nodes = function_nodes(info)
    for qual, fn_node in sorted(nodes.items()):
        if qual in traced_quals:
            continue  # traced bodies: geometry rules below, not taint
        walker = _ShardWalk(info, jit_names, jit_attrs, findings)
        for stmt in fn_node.body:
            walker.visit(stmt)
    _check_traced_branches(info, traced_quals, findings)
    # the replicated-pool check moved to spec-drift (the mesh fact
    # layer sees spec bindings through self-attrs and named specs this
    # rule's lexical scan could not)
    return findings
