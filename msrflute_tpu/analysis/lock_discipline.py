"""lock-discipline — ordered, non-blocking critical sections.

The host tail is the fleet's reliability bottleneck (the smart-NIC
server thesis, PAPERS.md): a stall inside a hot-path lock — the Tracer
lock every span emission takes, the dataset-cache lock every lazy read
takes, the checkpoint writer's condition — stalls every thread that
needs it, and an inconsistent acquisition order is a deadlock waiting
for the right interleaving.  Three checks over the concurrency facts:

1. **blocking while holding a lock** (hot-path modules incl. ``data/``
   and ``resilience/``): inside a ``with <lock>:`` region, flag direct
   blocking operations — ``open`` file IO, zero-arg ``.join()``,
   ``time.sleep``, ``.wait()`` on a DIFFERENT object (``cond.wait()``
   on the held condition is the sanctioned idiom: it releases the
   lock), explicit ``jax.device_get`` device syncs — and calls whose
   project-call-graph closure reaches such an operation (reported with
   the offending callee).  The shipped Tracer is the model citizen:
   span emission under its lock is a dict append; IO happens at
   ``flush()`` via buffered writes and outside-lock rewrites.

2. **acquisition order** (project-wide): every nested acquisition —
   lexically nested ``with`` regions, or a call made while holding lock
   A to a function that acquires lock B — contributes an A<B edge; a
   pair of locks acquired in both orders anywhere in the project flags
   both witnesses.

3. **explicit acquire without release**: a function that calls
   ``x.acquire()`` with no matching ``x.release()`` leaks the lock on
   any exception path — use ``with``.

Lock identity is the normalized attribute/name text (``self._mp_cond``
-> ``_mp_cond``); with-items whose final name segment does not look
like a lock (``lock``/``cond``/``mutex``/``sem``) are not tracked, so
``with tracer.span(...)`` never registers.  File IO is the builtin
``open`` only — serialization layers with their own locks (h5py) are
deliberately out of scope: serializing IO under a dedicated IO lock is
the user-blob reader's whole design.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, conc_hot_path

RULE = "lock-discipline"

_BLOCKING = {
    "file-io": "opens a file",
    "blocking-join": "joins `{d}`",
    "blocking-wait": "waits on `{d}`",
    "blocking-sleep": "sleeps",
}

#: memo key: (function, exempt lock) — the held lock travels into the
#: closure so `cond.wait()` on the HELD condition stays sanctioned even
#: when the wait loop is refactored into a helper
_MemoKey = Tuple[Tuple[str, str], str]


def _first_blocking(project: Project, key: Tuple[str, str],
                    memo: Dict[_MemoKey, Optional[Tuple]],
                    exempt_lock: str,
                    ) -> Optional[Tuple[str, str, int, str]]:
    """First blocking fact reachable from ``key`` (inclusive), as
    (kind, module::qual, line, detail); None when the closure is clean.
    ``blocking-wait`` on ``exempt_lock`` — the lock the caller holds —
    does not count (Condition.wait releases it).  Memoized across the
    project per exempt lock; cycles resolve to the memo's in-progress
    None."""
    mkey = (key, exempt_lock)
    if mkey in memo:
        return memo[mkey]
    memo[mkey] = None  # cycle guard: in-progress counts as clean
    fn = project.function(key)
    if fn is None:
        return None
    for kind, line, detail in fn.conc_ops:
        if kind not in _BLOCKING:
            continue
        if kind == "blocking-wait" and detail == exempt_lock:
            continue
        memo[mkey] = (kind, f"{fn.module}::{fn.qual}", line, detail)
        return memo[mkey]
    if fn.device_gets:
        line, arg, _ = fn.device_gets[0]
        memo[mkey] = ("device-sync", f"{fn.module}::{fn.qual}", line, arg)
        return memo[mkey]
    for ref, _line in fn.calls:
        callee = project.resolve(key[0], ref, fn.cls)
        if callee is None:
            continue
        found = _first_blocking(project, callee, memo, exempt_lock)
        if found is not None:
            memo[mkey] = found
            return found
    return None


def check_project(project: Project,
                  emit_paths: Optional[Set[str]] = None
                  ) -> List[Finding]:
    findings: List[Finding] = []
    memo: Dict[_MemoKey, Optional[Tuple]] = {}
    #: (outer lock, inner lock) -> first witness (module, line)
    order_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    for path in sorted(project.modules):
        mod = project.modules[path]
        for qual in sorted(mod.functions):
            fn = mod.functions[qual]
            regions = sorted(fn.lock_regions, key=lambda r: (r[1], -r[2]))
            # -- order edges from lexical nesting (any module) --------
            # sa == sb covers the multi-item form `with a_lock,
            # b_lock:` — both items share the statement's span, and the
            # stable sort keeps them in acquisition (item) order, so
            # the earlier item is the outer lock
            for i, (la, sa, ea) in enumerate(regions):
                for lb, sb, eb in regions[i + 1:]:
                    if sa <= sb and eb <= ea and la != lb:
                        order_edges.setdefault((la, lb), (path, sb))
            for la, sa, ea in regions:
                # -- order edges via callees that acquire -------------
                for ref, line in fn.calls:
                    if not sa <= line <= ea:
                        continue
                    callee = project.resolve(path, ref, fn.cls)
                    callee_fn = project.function(callee) if callee \
                        else None
                    if callee_fn is None:
                        continue
                    for lb, *_ in callee_fn.lock_regions:
                        if lb != la:
                            order_edges.setdefault((la, lb),
                                                   (path, line))
                if not conc_hot_path(path):
                    continue
                emit_ok = emit_paths is None or path in emit_paths
                # -- blocking while holding --------------------------
                for kind, line, detail in fn.conc_ops:
                    if not sa <= line <= ea or kind not in _BLOCKING:
                        continue
                    if kind == "blocking-wait" and detail == la:
                        continue  # cond.wait() releases the held lock
                    if emit_ok:
                        findings.append(Finding(
                            RULE, path, line,
                            f"`{fn.qual}` "
                            f"{_BLOCKING[kind].format(d=detail or '?')} "
                            f"while holding lock `{la}` — every thread "
                            "needing the lock stalls behind the IO/wait",
                            hint="move the blocking work outside the "
                                 "critical section: snapshot under the "
                                 "lock, do IO after (the Tracer flush "
                                 "and dataset-cache patterns)"))
                for line, arg, _loop in fn.device_gets:
                    if sa <= line <= ea and emit_ok:
                        findings.append(Finding(
                            RULE, path, line,
                            f"`{fn.qual}` device_get of `{arg}` while "
                            f"holding lock `{la}` — a device sync can "
                            "stall every thread needing the lock for a "
                            "full round",
                            hint="fetch before taking the lock; hold it "
                                 "only for the host-state update"))
                for ref, line in fn.calls:
                    if not sa <= line <= ea:
                        continue
                    callee = project.resolve(path, ref, fn.cls)
                    if callee is None:
                        continue
                    found = _first_blocking(project, callee, memo,
                                             la)
                    if found is not None and emit_ok:
                        kind, where, _bline, detail = found
                        phrase = _BLOCKING.get(kind, "syncs `{d}`")
                        findings.append(Finding(
                            RULE, path, line,
                            f"`{fn.qual}` calls `{ref}` while holding "
                            f"lock `{la}`, and `{where}` "
                            f"{phrase.format(d=detail or '?')} — "
                            "blocking inside the critical section",
                            hint="restructure so the lock guards only "
                                 "host-state mutation; do the blocking "
                                 "work before/after the `with` block"))
            # -- explicit acquire/release pairing ---------------------
            acquired = [(line, d) for k, line, d in fn.conc_ops
                        if k == "lock-acquire"]
            released = {d for k, _line, d in fn.conc_ops
                        if k == "lock-release"}
            if conc_hot_path(path) and \
                    (emit_paths is None or path in emit_paths):
                for line, lock in acquired:
                    if lock not in released:
                        findings.append(Finding(
                            RULE, path, line,
                            f"`{fn.qual}` acquires `{lock}` explicitly "
                            "with no release in the same function — an "
                            "exception between them leaks the lock "
                            "forever",
                            hint="use `with lock:` (releases on every "
                                 "path), or pair acquire/release in a "
                                 "try/finally"))

    # -- acquisition-order inversions (project-wide) -------------------
    for (la, lb), (path, line) in sorted(order_edges.items()):
        if (lb, la) not in order_edges or la > lb:
            continue  # report each inverted pair once per direction
        other_path, other_line = order_edges[(lb, la)]
        for p, ln, outer, inner, op, ol in (
                (path, line, la, lb, other_path, other_line),
                (other_path, other_line, lb, la, path, line)):
            if emit_paths is not None and p not in emit_paths:
                continue
            findings.append(Finding(
                RULE, p, ln,
                f"lock order inversion: `{inner}` is acquired while "
                f"holding `{outer}` here, but the opposite order is "
                f"taken at {op}:{ol} — two threads interleaving these "
                "paths deadlock",
                hint="pick one global acquisition order for the pair "
                     "and restructure the later acquisition out of the "
                     "other's critical section"))
    return findings
