"""transfer-budget — the one-fetch-per-round invariant, on the graph.

A faithful round pays ONE explicit ``jax.device_get`` per dtype group
(the flatpack packed-stats fetch) and nothing else crosses the
device->host boundary per round.  host-sync polices the *implicit*
syncs inside one module; this rule proves the *explicit* budget along
the actual round paths:

1. **round roots** — engine functions whose name matches
   :data:`ROUND_ROOT_RE` (``_drain_chunk``, ``_run_scaffold_round``,
   ``run_round``...): the entry points the per-round loop drives;
2. the project call graph is closed from each root, pruning callees
   whose name matches :data:`BOUNDARY_RE` — the eval/checkpoint-cadence
   functions whose fetches are sanctioned at their own (non-per-round)
   boundaries;
3. every function on a round path is held to the budget:

   - **split fetch** — more than one ``device_get`` site in one
     round-path function: each extra site is a transfer that a single
     bundled ``jax.device_get((a, b, c))`` would have amortized;
   - **loop fetch** — a ``device_get`` lexically inside a loop on a
     round path: one transfer PER ITERATION, the per-client fetch
     pattern the flatpack discipline exists to kill.

A deliberate second fetch (a value needed BEFORE the tail's bundle can
form, e.g. the scaffold weights feeding the control update) takes an
inline ``# flint: disable=transfer-budget <reason>`` naming the data
dependency.

Limitations (by design): value-flow through containers
(``chunk["stats"].fetch()``) is unresolvable statically — the packed
fetch that IS the budget lives behind exactly that pattern, which is
fine: the rule bounds the *extra* fetches around it.
"""

from __future__ import annotations

import re
from typing import List, Optional, Set, Tuple

from .core import Finding, Project

RULE = "transfer-budget"

#: engine functions that anchor a per-round path
ROUND_ROOT_RE = re.compile(r"(^|_)(run|drain)_?\w*?(round|chunk|tail)",
                           re.I)
#: the fleet pager's per-chunk entry points (engine/paging.py): the
#: server drives them through an attribute-of-attribute receiver
#: (``self.fleet_pager.prepare_chunk``) the call graph cannot resolve,
#: so they anchor their own round paths — the writeback's ONE explicit
#: fetch (and any force-completed early fetch, which reuses the same
#: site) is budget-checked like every other per-round transfer
PAGER_ROOT_RE = re.compile(
    r"^(prepare_chunk|queue_writeback|complete_writeback|"
    r"prefetch_chunk)$")
#: callees NOT on the per-round cadence (their own budgets apply at
#: their own boundaries): eval, checkpoint/persistence, prediction
#: dumps, replay, setup/teardown
BOUNDARY_RE = re.compile(
    r"(eval|checkpoint|ckpt|scorecard|predict|dump|replay|fall_back|"
    r"per_user|snapshot|save|load|close|finish|setup|init|flush)", re.I)

#: round roots live in engine modules
_ROOT_PARTS = ("engine",)
#: budget applies to hot-path modules reached from a root
_SCOPE_PARTS = ("engine", "strategies", "robust", "telemetry", "ops")


def _has_part(path: str, parts: Tuple[str, ...]) -> bool:
    segs = path.split("/")
    return any(p in segs for p in parts)


def check_project(project: Project,
                  emit_paths: Optional[Set[str]] = None
                  ) -> List[Finding]:
    roots = []
    for path, mod in project.modules.items():
        if not _has_part(path, _ROOT_PARTS):
            continue
        for qual, fn in mod.functions.items():
            if (ROUND_ROOT_RE.search(fn.name) or
                    PAGER_ROOT_RE.match(fn.name)) and \
                    not BOUNDARY_RE.search(fn.name):
                roots.append((path, qual))
    if not roots:
        return []
    parents = project.reachable_from(sorted(roots), stop=BOUNDARY_RE)

    findings: List[Finding] = []
    for key in sorted(parents):
        fn = project.function(key)
        if fn is None or not _has_part(fn.module, _SCOPE_PARTS):
            continue
        if emit_paths is not None and fn.module not in emit_paths:
            continue
        chain = project.call_path(parents, key)
        via = f" (round path: {' -> '.join(chain)})" if len(chain) > 1 \
            else ""
        loop_gets = [g for g in fn.device_gets if g[2]]
        flat_gets = [g for g in fn.device_gets if not g[2]]
        for line, arg, _ in loop_gets:
            findings.append(Finding(
                RULE, fn.module, line,
                f"device_get of `{arg}` inside a loop in round-path "
                f"function `{fn.qual}` — one transfer per iteration"
                + via,
                hint="hoist the fetch out of the loop: device_get the "
                     "whole array/tree once and index on host (the "
                     "flatpack single-transfer discipline)"))
        if len(flat_gets) > 1:
            for line, arg, _ in flat_gets[1:]:
                findings.append(Finding(
                    RULE, fn.module, line,
                    f"round-path function `{fn.qual}` pays "
                    f"{len(flat_gets)} explicit fetches — "
                    f"`device_get({arg})` splits the round's transfer "
                    "budget" + via,
                    hint="bundle the values into the function's first "
                         "fetch (`jax.device_get((a, b, ...))` is one "
                         "transfer) or suppress with the data "
                         "dependency that forces the ordering"))
    return findings
