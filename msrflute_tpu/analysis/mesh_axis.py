"""mesh-axis — collectives and specs name the canonical axis constants.

The fleet transfer plane hangs off ONE ``(clients, model)`` mesh whose
axis names are module constants (``parallel.mesh.CLIENTS_AXIS`` /
``MODEL_AXIS``).  A collective or ``P(...)`` spec spelled with a bare
string literal (``psum(x, "clients")``, ``P("clients")``) still runs —
until someone renames the axis, adds a second mesh, or copies the
string with a typo, at which point the program either crashes at trace
time (best case) or silently reduces over the WRONG axis (worst case:
a cross-client psum over the model axis averages unrelated shards).
The constants exist so that grep — and this rule — can prove every
collective targets the axis the layout doc says it does.

Scope: ``engine/``, ``parallel/``, ``strategies/`` — the modules that
own the mesh.  ``ops/`` kernels take their axis name as a PARAMETER
(axis-polymorphic library code) and are deliberately out of scope:
their axis argument classifies as ``dynamic``, never as a literal.

Facts come from the mesh fact layer (``FunctionSummary.collectives``,
``ModuleSummary.spec_literals``) — one summary walk, shared with
shard-locality and collective-budget.
"""

from __future__ import annotations

from typing import List, Optional

from .core import (Finding, ModuleInfo, Project, compute_module_summary)

RULE = "mesh-axis"

_SCOPE_PARTS = ("engine", "parallel", "strategies")


def _in_scope(info: ModuleInfo) -> bool:
    parts = info.path.split("/")
    return any(p in parts for p in _SCOPE_PARTS)


def check(info: ModuleInfo,
          project: Optional[Project] = None) -> List[Finding]:
    if not _in_scope(info):
        return []
    summary = project.modules.get(info.path) if project else None
    if summary is None:
        summary = compute_module_summary(info)
    findings: List[Finding] = []
    for fn in summary.functions.values():
        for op, line, axis in fn.collectives:
            if not axis.startswith("literal:"):
                continue
            lit = axis.split(":", 1)[1]
            findings.append(Finding(
                RULE, info.path, line,
                f"collective `{op}` names its mesh axis with the bare "
                f"string literal '{lit}' in `{fn.qual}`",
                hint="spell the axis with the canonical constant "
                     "(parallel.mesh.CLIENTS_AXIS / MODEL_AXIS): a "
                     "renamed or second mesh axis turns the stray "
                     "string into a wrong-axis reduction"))
    for lit, line in summary.spec_literals:
        findings.append(Finding(
            RULE, info.path, line,
            f"PartitionSpec names its mesh axis with the bare string "
            f"literal '{lit}'",
            hint="use P(CLIENTS_AXIS) / P(MODEL_AXIS) — the constants "
                 "keep every spec greppable and rename-safe against "
                 "the one mesh definition in parallel/mesh.py"))
    return findings
