"""fluteguard core — findings, suppressions, baseline, runner.

Pure stdlib (``ast`` + ``json``): the analyzer must import in any
environment — including shells where jax would claim the TPU tunnel —
and finish in seconds, because ``tests/test_flint_clean.py`` runs it
inside tier-1 on every verify.

Machinery:

- :class:`Finding` — one violation: rule id, file:line, message, fix
  hint.  The baseline key deliberately omits the line number so an
  unrelated edit above a baselined finding does not resurrect it.
- **Suppressions** — ``# flint: disable=RULE[,RULE2] reason`` on the
  offending line, or alone on the line directly above it.  A reason is
  mandatory and suppressions are themselves linted: one that stops
  matching any finding raises ``stale-suppression`` so dead pragmas
  cannot accumulate (the classic lint-rot failure mode).
- **Baseline** — ``analysis/baseline.json`` records accepted findings;
  the CLI exits non-zero only for findings outside it.  The shipped
  baseline is empty: new debt needs an inline suppression with a reason
  or a fix, never a silent baseline append.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: modules whose per-round cost rides the TPU queue — the host-sync rule
#: only applies here (cold paths may sync freely).  telemetry/ is in the
#: set because its whole contract is zero device syncs: a devbus
#: publisher spelled `.item()`/`float(...)` would silently turn the
#: packed-stats ride-along into per-scalar transfers.
HOT_PATH_PARTS = ("engine", "ops", "strategies", "telemetry", "robust")

_PRAGMA_RE = re.compile(
    r"#\s*flint:\s*disable=([A-Za-z0-9_,\-]+)(?:\s+(\S.*))?")


@dataclass(frozen=True)
class Finding:
    """One rule violation at file:line."""

    rule: str      #: rule id, e.g. ``host-sync``
    path: str      #: path relative to the analysis root, '/'-separated
    line: int      #: 1-based line number
    message: str   #: what is wrong, specific to the site
    hint: str = ""  #: how to fix it

    @property
    def baseline_key(self) -> str:
        # line-free on purpose: baselines must survive edits elsewhere
        # in the file
        return f"{self.rule}::{self.path}::{self.message}"

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclass
class Suppression:
    """One parsed ``# flint: disable=`` pragma."""

    path: str
    line: int            #: line the pragma sits on
    rules: Tuple[str, ...]
    reason: str
    applies_to: int      #: line the pragma suppresses (itself, or next)
    used: bool = False


@dataclass
class ModuleInfo:
    """One parsed source file handed to every per-file checker."""

    path: str            #: relative path ('/'-separated)
    abspath: str
    src: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    @property
    def is_hot_path(self) -> bool:
        parts = self.path.split("/")
        return any(p in parts for p in HOT_PATH_PARTS)


# ----------------------------------------------------------------------
# suppressions
# ----------------------------------------------------------------------
def parse_suppressions(info: ModuleInfo) -> List[Suppression]:
    """Pragmas from real COMMENT tokens only — a docstring QUOTING the
    syntax (this package's own docs) must not register as a pragma."""
    import io
    import tokenize

    out: List[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(info.src).readline))
    except (tokenize.TokenError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        # a pragma-only line shields the NEXT line; a trailing pragma
        # shields its own line
        own = info.lines[lineno - 1][: tok.start[1]].strip() \
            if lineno <= len(info.lines) else ""
        applies_to = lineno + 1 if not own else lineno
        out.append(Suppression(info.path, lineno, rules, reason, applies_to))
    return out


def apply_suppressions(findings: List[Finding],
                       suppressions: List[Suppression],
                       active_rules: Optional[Set[str]] = None
                       ) -> List[Finding]:
    """Drop suppressed findings, then append the suppression-hygiene
    findings (missing reason, stale pragma).  ``active_rules`` (a
    ``--rules`` subset) limits hygiene judgment to pragmas whose rules
    actually ran — a jit-purity pragma is not stale just because this
    invocation only ran host-sync."""
    by_site: Dict[Tuple[str, int], List[Suppression]] = {}
    for sup in suppressions:
        by_site.setdefault((sup.path, sup.applies_to), []).append(sup)

    kept: List[Finding] = []
    for f in findings:
        sups = [s for s in by_site.get((f.path, f.line), [])
                if f.rule in s.rules]
        if sups:
            for s in sups:
                s.used = True
            continue
        kept.append(f)

    for sup in suppressions:
        if active_rules is not None and \
                not set(sup.rules) & active_rules:
            continue
        if not sup.reason:
            kept.append(Finding(
                "bare-suppression", sup.path, sup.line,
                f"suppression of {','.join(sup.rules)} has no reason",
                hint="write `# flint: disable=RULE why it is safe here`"))
        if not sup.used:
            kept.append(Finding(
                "stale-suppression", sup.path, sup.line,
                f"suppression of {','.join(sup.rules)} matches no finding",
                hint="the code it shielded is gone or fixed — delete the "
                     "pragma"))
    return kept


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------
def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str]) -> Set[str]:
    if not path or not os.path.exists(path):
        return set()
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    keys = set()
    for entry in raw.get("entries", []):
        keys.add(f"{entry['rule']}::{entry['path']}::{entry['message']}")
    return keys


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "message": f.message} for f in findings]
    entries.sort(key=lambda e: (e["path"], e["rule"], e["line"]))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")


def filter_baseline(findings: List[Finding],
                    baseline: Set[str]) -> List[Finding]:
    return [f for f in findings if f.baseline_key not in baseline]


# ----------------------------------------------------------------------
# AST helpers shared by the checkers
# ----------------------------------------------------------------------
def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def const_int(node: ast.AST,
              consts: Optional[Dict[str, int]] = None) -> Optional[int]:
    """Fold an int literal, a module-constant Name, or +-* of those."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name) and consts and node.id in consts:
        return consts[node.id]
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv)):
        lhs = const_int(node.left, consts)
        rhs = const_int(node.right, consts)
        if lhs is None or rhs is None:
            return None
        if isinstance(node.op, ast.Add):
            return lhs + rhs
        if isinstance(node.op, ast.Sub):
            return lhs - rhs
        if isinstance(node.op, ast.Mult):
            return lhs * rhs
        return lhs // rhs if rhs else None
    return None


def module_int_constants(tree: ast.Module) -> Dict[str, int]:
    """Top-level ``NAME = <int expr>`` bindings (folded iteratively so
    constants may reference earlier ones)."""
    consts: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            val = const_int(node.value, consts)
            if val is not None:
                consts[node.targets[0].id] = val
    return consts


# ----------------------------------------------------------------------
# runner
# ----------------------------------------------------------------------
def _iter_py_files(paths: List[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(os.path.abspath(p))
        elif os.path.isdir(p):
            for base, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for name in sorted(names):
                    if name.endswith(".py"):
                        files.append(os.path.abspath(
                            os.path.join(base, name)))
    return sorted(set(files))


def load_module(abspath: str, root: str) -> ModuleInfo:
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    with open(abspath, "r", encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=abspath)
    except SyntaxError as exc:
        info = ModuleInfo(rel, abspath, src, ast.Module(body=[],
                                                        type_ignores=[]),
                          src.splitlines())
        info.parse_error = exc  # type: ignore[attr-defined]
        return info
    return ModuleInfo(rel, abspath, src, tree, src.splitlines())


def analyze(paths: List[str], root: Optional[str] = None,
            rules: Optional[Set[str]] = None) -> List[Finding]:
    """Run every checker over ``paths``; returns suppression-filtered
    findings (baseline NOT applied — that is the caller's policy)."""
    from . import donation, host_sync, jit_purity, pallas_shape, \
        put_loop, schema_drift

    root = os.path.abspath(root or os.getcwd())
    per_file_checkers = [
        (host_sync.RULE, host_sync.check),
        (donation.RULE, donation.check),
        (jit_purity.RULE, jit_purity.check),
        (pallas_shape.RULE, pallas_shape.check),
        (put_loop.RULE, put_loop.check),
    ]

    findings: List[Finding] = []
    suppressions: List[Suppression] = []
    for abspath in _iter_py_files(paths):
        info = load_module(abspath, root)
        if getattr(info, "parse_error", None) is not None:
            exc = info.parse_error  # type: ignore[attr-defined]
            findings.append(Finding("parse-error", info.path,
                                    exc.lineno or 1, str(exc.msg)))
            continue
        suppressions.extend(parse_suppressions(info))
        for rule, check in per_file_checkers:
            if rules and rule not in rules:
                continue
            findings.extend(check(info))

    if rules is None or schema_drift.RULE in rules:
        findings.extend(schema_drift.check_project(root))
        # schema-drift findings live in .py/.md files that may carry
        # inline pragmas too; only .py pragmas are parsed, which is fine
        # because the actionable end of a drift is always the schema.

    return apply_suppressions(findings, suppressions, active_rules=rules)
