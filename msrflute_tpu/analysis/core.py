"""fluteguard core — findings, suppressions, baseline, runner.

Pure stdlib (``ast`` + ``json``): the analyzer must import in any
environment — including shells where jax would claim the TPU tunnel —
and finish in seconds, because ``tests/test_flint_clean.py`` runs it
inside tier-1 on every verify.

Machinery:

- :class:`Finding` — one violation: rule id, file:line, message, fix
  hint.  The baseline key deliberately omits the line number so an
  unrelated edit above a baselined finding does not resurrect it.
- **Suppressions** — ``# flint: disable=RULE[,RULE2] reason`` on the
  offending line, or alone on the line directly above it.  A reason is
  mandatory and suppressions are themselves linted: one that stops
  matching any finding raises ``stale-suppression`` so dead pragmas
  cannot accumulate (the classic lint-rot failure mode).
- **Baseline** — ``analysis/baseline.json`` records accepted findings;
  the CLI exits non-zero only for findings outside it.  The shipped
  baseline is empty: new debt needs an inline suppression with a reason
  or a fix, never a silent baseline append.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

#: modules whose per-round cost rides the TPU queue — the host-sync rule
#: only applies here (cold paths may sync freely).  telemetry/ is in the
#: set because its whole contract is zero device syncs: a devbus
#: publisher spelled `.item()`/`float(...)` would silently turn the
#: packed-stats ride-along into per-scalar transfers.
HOT_PATH_PARTS = ("engine", "ops", "strategies", "telemetry", "robust")

#: the concurrency rules' wider scope: everything above plus the layers
#: that own threads, locks and durable writes — the resilience handlers
#: and the data-cache/user-blob locks.  One tuple, shared by
#: lock-discipline and thread-escape, so a future package (fleet/?)
#: joins every concurrency checker with one edit.
CONC_HOT_PARTS = HOT_PATH_PARTS + ("resilience", "data")


def conc_hot_path(path: str) -> bool:
    segs = path.split("/")
    return any(p in segs for p in CONC_HOT_PARTS)

#: every rule id the suite can emit.  Lives here (not __init__) so the
#: suppression linter can judge pragma validity without an import cycle.
RULES = ("host-sync", "donation-aliasing", "jit-purity", "pallas-shape",
         "put-loop", "schema-drift", "shard-ready", "recompile-hazard",
         "transfer-budget", "guard-matrix", "event-schema",
         "signal-safety", "lock-discipline", "thread-escape",
         "atomic-write",
         "mesh-axis", "shard-locality", "spec-drift", "collective-budget",
         "stale-suppression", "bare-suppression", "unknown-suppression",
         "parse-error")

#: rule-rename migration map: old pragma spelling -> current rule id.  A
#: pragma naming a rule that no longer exists is an ERROR
#: (``unknown-suppression``), never silently inert; when the old name is
#: here the finding's hint names the replacement.  Seeded with the
#: underscore spellings (the one misspelling every rule accumulates).
RULE_RENAMES = {
    "host_sync": "host-sync",
    "donation_aliasing": "donation-aliasing",
    "jit_purity": "jit-purity",
    "pallas_shape": "pallas-shape",
    "put_loop": "put-loop",
    "schema_drift": "schema-drift",
    "shard_ready": "shard-ready",
    "recompile_hazard": "recompile-hazard",
    "transfer_budget": "transfer-budget",
    "guard_matrix": "guard-matrix",
    "event_schema": "event-schema",
    "signal_safety": "signal-safety",
    "lock_discipline": "lock-discipline",
    "thread_escape": "thread-escape",
    "atomic_write": "atomic-write",
    "mesh_axis": "mesh-axis",
    "shard_locality": "shard-locality",
    "spec_drift": "spec-drift",
    "collective_budget": "collective-budget",
}

#: factories whose RESULT is a compiled callable — shared by host-sync
#: (taint seeding), the summary extractor (cross-module jitted-binding
#: tracking) and recompile-hazard (static_argnums hazards)
JIT_FACTORIES = {"jax.jit", "jit", "jax.pmap", "pmap", "shard_map",
                 "jax.experimental.shard_map.shard_map", "pl.pallas_call",
                 "pallas_call"}

#: calls whose named function arguments become TRACED bodies — shared by
#: jit-purity (root discovery) and the summary extractor
TRACE_ENTRY = {"jax.jit", "jit", "jax.pmap", "pmap", "shard_map",
               "jax.experimental.shard_map.shard_map", "jax.vmap", "vmap",
               "jax.lax.scan", "lax.scan", "jax.lax.while_loop",
               "lax.while_loop", "jax.lax.fori_loop", "lax.fori_loop",
               "jax.lax.cond", "lax.cond", "jax.checkpoint", "jax.remat",
               "pl.pallas_call", "pallas_call", "jax.grad",
               "jax.value_and_grad"}

_PRAGMA_RE = re.compile(
    r"#\s*flint:\s*disable=([A-Za-z0-9_,\-]+)(?:\s+(\S.*))?")


@dataclass(frozen=True)
class Finding:
    """One rule violation at file:line."""

    rule: str      #: rule id, e.g. ``host-sync``
    path: str      #: path relative to the analysis root, '/'-separated
    line: int      #: 1-based line number
    message: str   #: what is wrong, specific to the site
    hint: str = ""  #: how to fix it

    @property
    def baseline_key(self) -> str:
        # line-free on purpose: baselines must survive edits elsewhere
        # in the file
        return f"{self.rule}::{self.path}::{self.message}"

    @property
    def id(self) -> str:
        """Stable finding id for machine consumers (``--format json`` /
        SARIF ``partialFingerprints``): the rule plus a hash of the
        line-free baseline key, so the id survives unrelated edits in
        the same file exactly like the baseline does."""
        digest = hashlib.sha1(
            self.baseline_key.encode("utf-8")).hexdigest()[:12]
        return f"{self.rule}-{digest}"

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclass
class Suppression:
    """One parsed ``# flint: disable=`` pragma."""

    path: str
    line: int            #: line the pragma sits on
    rules: Tuple[str, ...]
    reason: str
    applies_to: int      #: line the pragma suppresses (itself, or next)
    used: bool = False
    #: hygiene findings (stale/bare/unknown) are only judged for pragmas
    #: in files the caller actually asked to analyze — a project-wide
    #: summary pass may parse pragmas in files outside the request
    #: purely so cross-file checkers' findings can be suppressed there
    in_scope: bool = True


@dataclass
class ModuleInfo:
    """One parsed source file handed to every per-file checker."""

    path: str            #: relative path ('/'-separated)
    abspath: str
    src: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    @property
    def is_hot_path(self) -> bool:
        parts = self.path.split("/")
        return any(p in parts for p in HOT_PATH_PARTS)


# ----------------------------------------------------------------------
# suppressions
# ----------------------------------------------------------------------
def parse_suppressions(info: ModuleInfo) -> List[Suppression]:
    """Pragmas from real COMMENT tokens only — a docstring QUOTING the
    syntax (this package's own docs) must not register as a pragma."""
    import io
    import tokenize

    out: List[Suppression] = []
    if "flint:" not in info.src:
        return out  # fast path: tokenizing is ~10x a parse
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(info.src).readline))
    except (tokenize.TokenError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        # a pragma-only line shields the NEXT line; a trailing pragma
        # shields its own line
        own = info.lines[lineno - 1][: tok.start[1]].strip() \
            if lineno <= len(info.lines) else ""
        applies_to = lineno + 1 if not own else lineno
        out.append(Suppression(info.path, lineno, rules, reason, applies_to))
    return out


def apply_suppressions(findings: List[Finding],
                       suppressions: List[Suppression],
                       active_rules: Optional[Set[str]] = None
                       ) -> List[Finding]:
    """Drop suppressed findings, then append the suppression-hygiene
    findings (missing reason, stale pragma).  ``active_rules`` (a
    ``--rules`` subset) limits hygiene judgment to pragmas whose rules
    actually ran — a jit-purity pragma is not stale just because this
    invocation only ran host-sync."""
    by_site: Dict[Tuple[str, int], List[Suppression]] = {}
    for sup in suppressions:
        by_site.setdefault((sup.path, sup.applies_to), []).append(sup)

    kept: List[Finding] = []
    for f in findings:
        sups = [s for s in by_site.get((f.path, f.line), [])
                if f.rule in s.rules]
        if sups:
            for s in sups:
                s.used = True
            continue
        kept.append(f)

    for sup in suppressions:
        if not sup.in_scope:
            continue
        # pragma validity is judged regardless of any --rules subset: a
        # pragma naming a rule that no longer exists must be an ERROR,
        # not silently inert (the rule-rename failure mode)
        unknown = [r for r in sup.rules if r not in RULES]
        for r in unknown:
            renamed = RULE_RENAMES.get(r)
            kept.append(Finding(
                "unknown-suppression", sup.path, sup.line,
                f"suppression names unknown rule `{r}`"
                + (f" (renamed to `{renamed}`)" if renamed else ""),
                hint=(f"update the pragma to `disable={renamed}`"
                      if renamed else
                      "no such rule — fix the spelling or delete the "
                      "pragma (tools/flint --list-rules)")))
        if unknown and not (set(sup.rules) & set(RULES)):
            continue  # nothing valid left to judge for staleness
        if active_rules is not None and \
                not set(sup.rules) & active_rules:
            continue
        if not sup.reason:
            kept.append(Finding(
                "bare-suppression", sup.path, sup.line,
                f"suppression of {','.join(sup.rules)} has no reason",
                hint="write `# flint: disable=RULE why it is safe here`"))
        if not sup.used:
            kept.append(Finding(
                "stale-suppression", sup.path, sup.line,
                f"suppression of {','.join(sup.rules)} matches no finding",
                hint="the code it shielded is gone or fixed — delete the "
                     "pragma"))
    return kept


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------
def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str]) -> Set[str]:
    if not path or not os.path.exists(path):
        return set()
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    keys = set()
    for entry in raw.get("entries", []):
        keys.add(f"{entry['rule']}::{entry['path']}::{entry['message']}")
    return keys


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "message": f.message} for f in findings]
    entries.sort(key=lambda e: (e["path"], e["rule"], e["line"]))
    # tmp + replace: the committed baseline is a durable artifact — a
    # crash mid-write must not leave a torn JSON that makes
    # every later run fail to parse it (the atomic-write discipline)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def filter_baseline(findings: List[Finding],
                    baseline: Set[str]) -> List[Finding]:
    return [f for f in findings if f.baseline_key not in baseline]


# ----------------------------------------------------------------------
# AST helpers shared by the checkers
# ----------------------------------------------------------------------
def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def const_int(node: ast.AST,
              consts: Optional[Dict[str, int]] = None) -> Optional[int]:
    """Fold an int literal, a module-constant Name, or +-* of those."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name) and consts and node.id in consts:
        return consts[node.id]
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv)):
        lhs = const_int(node.left, consts)
        rhs = const_int(node.right, consts)
        if lhs is None or rhs is None:
            return None
        if isinstance(node.op, ast.Add):
            return lhs + rhs
        if isinstance(node.op, ast.Sub):
            return lhs - rhs
        if isinstance(node.op, ast.Mult):
            return lhs * rhs
        return lhs // rhs if rhs else None
    return None


def module_int_constants(tree: ast.Module) -> Dict[str, int]:
    """Top-level ``NAME = <int expr>`` bindings (folded iteratively so
    constants may reference earlier ones)."""
    consts: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            val = const_int(node.value, consts)
            if val is not None:
                consts[node.targets[0].id] = val
    return consts


# ----------------------------------------------------------------------
# interprocedural engine (flint v2)
#
# One pass per file extracts a JSON-serializable :class:`ModuleSummary`
# (functions + their call sites / fetch sites / self-state reads &
# writes, imports, jitted bindings, traced roots, class markers, event
# emissions).  :class:`Project` stitches the summaries into a project-
# wide call graph with cross-module resolution, and exposes the two
# reachability queries the checkers need: trace-context closure
# (jit-purity, shard-ready, recompile-hazard) and round-path closure
# (transfer-budget).  Summaries are cached per file keyed by
# (mtime_ns, size) — in memory for repeated in-process runs (the tier-1
# gate + test suite), and optionally on disk for ``tools/flint
# --changed`` so an incremental run re-parses only the edited files.
# ----------------------------------------------------------------------
@dataclass
class FunctionSummary:
    """Def-use facts for one function/method, enough for every project
    checker to reason about it WITHOUT re-parsing its file."""

    module: str                 #: rel path of the defining file
    qual: str                   #: dotted qualname ("Cls.meth", "f.inner")
    name: str                   #: bare name
    cls: Optional[str]          #: immediately enclosing class, if any
    line: int
    #: every call site: (dotted name as written, line)
    calls: List[Tuple[str, int]] = field(default_factory=list)
    #: explicit fetches: (line, arg source, lexically-inside-loop)
    device_gets: List[Tuple[int, str, bool]] = field(default_factory=list)
    #: ``self.X`` attribute loads / stores (recompile-hazard's
    #: mutable-capture cross-check)
    self_reads: List[str] = field(default_factory=list)
    self_writes: List[str] = field(default_factory=list)
    # -- concurrency fact layer (signal-safety / lock-discipline /
    # -- thread-escape ride these; see the module comment) -------------
    #: lock-held regions: (lock id, start line, end line) from ``with``
    #: statements whose context expression names a lock/condition
    lock_regions: List[Tuple[str, int, int]] = field(default_factory=list)
    #: concurrency-relevant operations: (kind, line, detail); kind one of
    #: lock-acquire / lock-release / file-io / log / blocking-join /
    #: blocking-wait / blocking-sleep
    conc_ops: List[Tuple[str, int, str]] = field(default_factory=list)
    #: line spans of ``if`` statements whose test names a
    #: ``*_from_signal``-style flag — the deferred-flush idiom
    #: signal-safety blesses (work gated on the flag runs outside
    #: signal context)
    deferred_spans: List[Tuple[int, int]] = field(default_factory=list)
    #: direct ``self.X = <expr>`` assignments: (attr, line, value src)
    self_assigns: List[Tuple[str, int, str]] = field(default_factory=list)
    #: simple local ``name = <expr>`` bindings (last wins) — one level
    #: of value provenance for thread-escape's snapshot check
    local_assigns: Dict[str, str] = field(default_factory=dict)
    # -- mesh fact layer (mesh-axis / shard-locality /
    # -- collective-budget ride these; see the module comment) ----------
    #: collective call sites: (op tail, line, axis desc); axis desc is
    #: :func:`axis_desc_of`'s classification of the axis argument
    collectives: List[Tuple[str, int, str]] = field(default_factory=list)
    #: pool-table gathers: (base name, slice source, line) — Subscript
    #: loads whose base names a slot-axis table and whose slice looks
    #: like slot ids (``.at[...]`` update chains are scatters, not
    #: gathers, and are excluded)
    slot_gathers: List[Tuple[str, str, int]] = field(default_factory=list)
    #: sentinel-padded scatters: (base name, line) from
    #: ``pool.at[slots].set(..., mode="drop")`` — the fixed-shape
    #: page-in idiom shard-locality accepts as shard-local evidence
    drop_scatters: List[Tuple[str, int]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"module": self.module, "qual": self.qual,
                "name": self.name, "cls": self.cls, "line": self.line,
                "calls": [list(c) for c in self.calls],
                "device_gets": [list(d) for d in self.device_gets],
                "self_reads": self.self_reads,
                "self_writes": self.self_writes,
                "lock_regions": [list(r) for r in self.lock_regions],
                "conc_ops": [list(o) for o in self.conc_ops],
                "deferred_spans": [list(s) for s in self.deferred_spans],
                "self_assigns": [list(a) for a in self.self_assigns],
                "local_assigns": self.local_assigns,
                "collectives": [list(c) for c in self.collectives],
                "slot_gathers": [list(g) for g in self.slot_gathers],
                "drop_scatters": [list(s) for s in self.drop_scatters]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FunctionSummary":
        return cls(d["module"], d["qual"], d["name"], d.get("cls"),
                   d["line"],
                   [tuple(c) for c in d.get("calls", [])],
                   [tuple(g) for g in d.get("device_gets", [])],
                   list(d.get("self_reads", [])),
                   list(d.get("self_writes", [])),
                   [tuple(r) for r in d.get("lock_regions", [])],
                   [tuple(o) for o in d.get("conc_ops", [])],
                   [tuple(s) for s in d.get("deferred_spans", [])],
                   [tuple(a) for a in d.get("self_assigns", [])],
                   dict(d.get("local_assigns", {})),
                   [tuple(c) for c in d.get("collectives", [])],
                   [tuple(g) for g in d.get("slot_gathers", [])],
                   [tuple(s) for s in d.get("drop_scatters", [])])


@dataclass
class ModuleSummary:
    """One file's interprocedural facts (see module comment)."""

    path: str                               #: rel path
    functions: Dict[str, FunctionSummary] = field(default_factory=dict)
    #: local name -> (target rel path, attr or None for module imports);
    #: only imports that resolve INSIDE the analyzed project are kept
    imports: Dict[str, Tuple[str, Optional[str]]] = \
        field(default_factory=dict)
    #: bare name -> qual of the LAST def with that name (runtime
    #: shadowing semantics, matching the old jit-purity index)
    name_index: Dict[str, str] = field(default_factory=dict)
    #: names / self-attrs bound to a jit-factory result
    jit_names: List[str] = field(default_factory=list)
    jit_attrs: List[str] = field(default_factory=list)
    #: trace roots: (function ref as written, enclosing class or None)
    traced_roots: List[Tuple[str, Optional[str]]] = \
        field(default_factory=list)
    #: jit factories declaring static args: binding name/attr ->
    #: {"argnums": [...], "argnames": [...], "line": n}
    static_jit: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    #: class -> list of base-class names (dotted, as written)
    class_bases: Dict[str, List[str]] = field(default_factory=dict)
    #: class -> {attr: constant} for simple class-level constants
    #: (``host_rounds = True`` markers, guard-matrix's strategy scan)
    class_markers: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    #: telemetry emissions: (event name, line, api); api one of
    #: log_event / emit_event / event / kind-literal; a trailing ``*``
    #: in the name marks an f-string prefix family (``watchdog_*``)
    events: List[Tuple[str, int, str]] = field(default_factory=list)
    #: devbus publishes: (metric name, line, publish|devbus_host)
    devbus: List[Tuple[str, int, str]] = field(default_factory=list)
    #: thread spawns: (target ref as written or "", line, has name= kw,
    #: enclosing class or None, enclosing function qual or "")
    thread_spawns: List[Tuple[str, int, bool, Optional[str], str]] = \
        field(default_factory=list)
    #: ``signal.signal(sig, handler)`` registrations:
    #: (handler ref as written, line, enclosing class or None)
    signal_handlers: List[Tuple[str, int, Optional[str]]] = \
        field(default_factory=list)
    # -- mesh fact layer ------------------------------------------------
    #: per-lane trace roots — refs handed to vmap / lax.scan:
    #: (ref as written, enclosing class or None, enclosing function
    #: qual or "" — nested lane bodies resolve in their BUILDER's
    #: scope, not via the module-wide last-def name index)
    lane_roots: List[Tuple[str, Optional[str], str]] = \
        field(default_factory=list)
    #: shard_map roots: (ref, enclosing class or None, enclosing
    #: function qual or "", line) — the enclosing qual lets
    #: shard-locality read the BUILDER's locals for shard-local markers
    shardmap_roots: List[Tuple[str, Optional[str], str, int]] = \
        field(default_factory=list)
    #: sharding-spec bindings: (bound name — ``x`` or ``self.x`` —,
    #: kind per :func:`spec_kind_of`, line)
    spec_bindings: List[Tuple[str, str, int]] = \
        field(default_factory=list)
    #: ``P("...")`` string-literal axis specs: (axis string, line)
    spec_literals: List[Tuple[str, int]] = field(default_factory=list)
    #: device_put sites: (target source, spec desc, line, enclosing
    #: function qual or ""); spec desc is ``none`` (no sharding arg), a
    #: :func:`spec_kind_of` kind, or ``name:<dotted>`` for a spec passed
    #: by name (resolved against spec_bindings by spec-drift)
    device_puts: List[Tuple[str, str, int, str]] = \
        field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "functions": {q: f.to_dict()
                          for q, f in self.functions.items()},
            "imports": {k: list(v) for k, v in self.imports.items()},
            "name_index": self.name_index,
            "jit_names": self.jit_names, "jit_attrs": self.jit_attrs,
            "traced_roots": [list(t) for t in self.traced_roots],
            "static_jit": self.static_jit,
            "class_bases": self.class_bases,
            "class_markers": self.class_markers,
            "events": [list(e) for e in self.events],
            "devbus": [list(d) for d in self.devbus],
            "thread_spawns": [list(t) for t in self.thread_spawns],
            "signal_handlers": [list(h) for h in self.signal_handlers],
            "lane_roots": [list(t) for t in self.lane_roots],
            "shardmap_roots": [list(t) for t in self.shardmap_roots],
            "spec_bindings": [list(b) for b in self.spec_bindings],
            "spec_literals": [list(s) for s in self.spec_literals],
            "device_puts": [list(p) for p in self.device_puts],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModuleSummary":
        out = cls(d["path"])
        out.functions = {q: FunctionSummary.from_dict(f)
                         for q, f in d.get("functions", {}).items()}
        out.imports = {k: (v[0], v[1])
                       for k, v in d.get("imports", {}).items()}
        out.name_index = dict(d.get("name_index", {}))
        out.jit_names = list(d.get("jit_names", []))
        out.jit_attrs = list(d.get("jit_attrs", []))
        out.traced_roots = [(t[0], t[1])
                            for t in d.get("traced_roots", [])]
        out.static_jit = dict(d.get("static_jit", {}))
        out.class_bases = {k: list(v)
                           for k, v in d.get("class_bases", {}).items()}
        out.class_markers = {k: dict(v)
                             for k, v in d.get("class_markers", {}).items()}
        out.events = [(e[0], e[1], e[2]) for e in d.get("events", [])]
        out.devbus = [(e[0], e[1], e[2]) for e in d.get("devbus", [])]
        out.thread_spawns = [(t[0], t[1], bool(t[2]), t[3], t[4])
                             for t in d.get("thread_spawns", [])]
        out.signal_handlers = [(h[0], h[1], h[2])
                               for h in d.get("signal_handlers", [])]
        out.lane_roots = [(t[0], t[1], t[2])
                          for t in d.get("lane_roots", [])]
        out.shardmap_roots = [(t[0], t[1], t[2], t[3])
                              for t in d.get("shardmap_roots", [])]
        out.spec_bindings = [(b[0], b[1], b[2])
                             for b in d.get("spec_bindings", [])]
        out.spec_literals = [(s[0], s[1])
                             for s in d.get("spec_literals", [])]
        out.device_puts = [(p[0], p[1], p[2], p[3])
                           for p in d.get("device_puts", [])]
        return out


_EVENT_APIS = {"log_event": 0, "emit_event": 1}
_DEVGET_NAMES = ("jax.device_get", "device_get")
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)

# -- concurrency fact layer --------------------------------------------
#: a with-statement context expression whose final name segment matches
#: this is treated as a lock acquisition (threading.Lock / RLock /
#: Condition / Semaphore attribute naming conventions)
_LOCK_NAME_RE = re.compile(r"(lock|cond|mutex|sem)", re.I)
#: an ``if`` test naming one of these flags marks its body as DEFERRED
#: out of signal context — the blessed deferred-flush idiom (the
#: handler sets a flag; the loop's next poll does the unsafe work)
_SIGNAL_FLAG_RE = re.compile(r"from_signal|in_signal|signal_ctx", re.I)
_THREAD_FACTORIES = {"threading.Thread", "Thread"}

# -- mesh fact layer ---------------------------------------------------
#: collective primitives whose second argument (first for axis_index)
#: names a mesh axis.  ``axis_index`` rides along because
#: shard-locality treats it as the global->block-local slot-id
#: conversion evidence, not as a cross-shard collective.
COLLECTIVE_OPS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                  "ppermute", "all_to_all", "psum_scatter", "pshuffle"}
#: names/attrs whose FINAL segment is a canonical axis constant — the
#: only sanctioned way to spell an axis in engine//parallel//strategies/
_AXIS_CONST_RE = re.compile(r"(CLIENTS_AXIS|MODEL_AXIS)$")
#: per-lane trace entries (the vmapped/scanned per-client body) vs the
#: per-shard ones (shard_map): shard-locality prohibits collectives in
#: the former and audits gathers in the latter
_LANE_ENTRIES = {"jax.vmap", "vmap", "jax.lax.scan", "lax.scan"}
_SHARD_MAP_ENTRIES = {"shard_map", "jax.experimental.shard_map.shard_map"}
_PARTITION_SPEC_TAILS = ("P", "PartitionSpec")
#: parallel/-helper tails that construct a sharding of known kind
_SPEC_HELPER_KINDS = {"slot_pool_sharding": "clients",
                      "client_axis_sharding": "clients",
                      "replicated_sharding": "replicated"}
_DEVICE_PUT_NAMES = ("jax.device_put", "device_put")

#: identifier tokens that mark a SLOT-AXIS table (the fleet page pool,
#: carry-row buffers).  Shared by the summary extractor (slot-gather /
#: drop-scatter / device_put facts) and spec-drift's replicated-pool
#: check (moved here from shard-ready).
POOL_TOKENS = frozenset({"row", "rows", "pool", "slot", "slots",
                         "table", "tables"})
_TOKEN_SPLIT = re.compile(r"[^a-zA-Z0-9]+")
#: a Subscript slice that looks like slot ids (directly or through one
#: local binding) marks a pool gather
_SLOT_SLICE_RE = re.compile(r"(slot|idx|ids|indices)", re.I)


def pool_name(name: Optional[str]) -> bool:
    """``rows`` / ``page_pool`` / ``self._tables`` — a slot-axis table
    name by its identifier tokens."""
    if not name:
        return False
    return any(tok in POOL_TOKENS
               for tok in _TOKEN_SPLIT.split(name.lower()))


def axis_desc_of(node: Optional[ast.AST]) -> str:
    """Classify a collective's axis argument: ``const:<NAME>`` for the
    canonical constants, ``literal:<s>`` for a bare string, ``dynamic``
    for everything else (parameterized axis-library kernels)."""
    if node is None:
        return "dynamic"
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return f"literal:{node.value}"
    name = dotted_name(node)
    if name is not None:
        m = _AXIS_CONST_RE.search(name.rsplit(".", 1)[-1])
        if m:
            return f"const:{m.group(1)}"
    if isinstance(node, (ast.Tuple, ast.List)):
        descs = [axis_desc_of(e) for e in node.elts]
        lit = next((d for d in descs if d.startswith("literal:")), None)
        if lit:
            return lit
        if descs and all(d.startswith("const:") for d in descs):
            return descs[0]
    return "dynamic"


def spec_kind_of(node: Optional[ast.AST]) -> Optional[str]:
    """Classify a sharding-spec expression — ``NamedSharding(mesh,
    P(...))``, a bare ``P(...)`` literal, or a parallel/ helper call —
    as replicated / clients / model / dynamic.  None when the
    expression is not a spec construction at all."""
    if not isinstance(node, ast.Call):
        return None
    tail = (call_name(node) or "").split(".")[-1]
    if tail in _SPEC_HELPER_KINDS:
        return _SPEC_HELPER_KINDS[tail]
    if tail == "NamedSharding":
        if len(node.args) < 2:
            return "dynamic"
        return spec_kind_of(node.args[1]) or "dynamic"
    if tail in _PARTITION_SPEC_TAILS:
        if any(isinstance(a, ast.Starred) for a in node.args):
            return "dynamic"
        if not node.args and not node.keywords:
            return "replicated"
        descs = [axis_desc_of(a) for a in node.args]
        if any(d == "const:CLIENTS_AXIS" for d in descs):
            return "clients"
        if any(d == "const:MODEL_AXIS" for d in descs):
            return "model"
        return "dynamic"
    return None
#: logger-receiver names whose level-method calls count as logging
_LOGGER_RECV_RE = re.compile(r"(^|\.)(_?logger|log)$", re.I)
_LOG_LEVEL_TAILS = {"debug", "info", "warning", "warn", "error",
                    "exception", "critical", "log"}


def lock_id_of(expr: ast.AST) -> Optional[str]:
    """Normalized lock identity for a with-item / acquire receiver:
    ``self._mp_cond`` -> ``_mp_cond``; inline ``threading.Lock()`` keeps
    its dotted factory name.  None when the expression does not look
    like a lock."""
    name = dotted_name(expr)
    if name is None and isinstance(expr, ast.Call):
        name = call_name(expr)
    if name is None:
        return None
    if not _LOCK_NAME_RE.search(name.rsplit(".", 1)[-1]):
        return None
    return name[5:] if name.startswith("self.") else name


def open_mode(call: ast.Call) -> Optional[str]:
    """The literal mode of an ``open(...)`` call (positional or
    ``mode=``), or None when absent/non-literal.  Shared by the summary
    extractor and atomic-write."""
    mode: Optional[str] = None
    if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
        mode = str(call.args[1].value)
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = str(kw.value.value)
    return mode


def _module_rel_for(dotted: str, importer: str, level: int,
                    known: Set[str]) -> Optional[str]:
    """Map an import to a rel path inside the project file set.

    ``known`` holds the project's rel paths.  Handles relative imports
    (``from ..telemetry import metrics``) by walking up from the
    importer's package, and absolute ones by trying the dotted path both
    as-is and package-qualified (``msrflute_tpu.engine.round``)."""
    candidates: List[str] = []
    if level > 0:
        base = importer.split("/")[:-1]           # importer's package dir
        base = base[: len(base) - (level - 1)] if level > 1 else base
        if len(importer.split("/")) - 1 >= level - 1:
            candidates.append("/".join(base + dotted.split("."))
                              if dotted else "/".join(base))
    else:
        candidates.append("/".join(dotted.split(".")))
    out = []
    for cand in candidates:
        if not cand:
            continue
        if cand + ".py" in known:
            return cand + ".py"
        if cand + "/__init__.py" in known:
            return cand + "/__init__.py"
        out.append(cand)
    return None


class _SummaryVisitor(ast.NodeVisitor):
    """One walk of a module AST building its :class:`ModuleSummary`."""

    def __init__(self, info: ModuleInfo, summary: ModuleSummary):
        self.info = info
        self.s = summary
        self.class_stack: List[str] = []
        self.fn_stack: List[FunctionSummary] = []
        self.loop_depth = 0

    # -- context ----------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.s.class_bases[node.name] = [
            n for n in (dotted_name(b) for b in node.bases) if n]
        markers = {}
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) and \
                    isinstance(stmt.value, ast.Constant):
                markers[stmt.targets[0].id] = stmt.value.value
        if markers:
            self.s.class_markers[node.name] = markers
        self.generic_visit(node)
        self.class_stack.pop()

    def _enter_fn(self, node) -> None:
        prefix = ""
        if self.fn_stack:
            prefix = self.fn_stack[-1].qual + "."
        elif self.class_stack:
            prefix = ".".join(self.class_stack) + "."
        qual = prefix + node.name
        fn = self.s.functions.get(qual)
        if fn is None:
            fn = FunctionSummary(self.info.path, qual, node.name,
                                 self.class_stack[-1] if self.class_stack
                                 else None, node.lineno)
            self.s.functions[qual] = fn
        # else: conditional redefinition (`if mode: def f ... else:
        # def f`) — accumulate into ONE summary so the facts are the
        # UNION of the branches (either def may be the one traced;
        # round.py's gather_axis all_gather lives in one branch only)
        self.s.name_index[node.name] = qual
        for dec in node.decorator_list:
            dec_call = dec.func if isinstance(dec, ast.Call) else dec
            if dotted_name(dec_call) in TRACE_ENTRY:
                self.s.traced_roots.append(
                    (node.name, fn.cls))
        self.fn_stack.append(fn)
        outer_loop, self.loop_depth = self.loop_depth, 0
        self.generic_visit(node)
        self.loop_depth = outer_loop
        self.fn_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_fn(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._enter_fn(node)

    # -- imports ----------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            target = _module_rel_for(alias.name, self.info.path, 0,
                                     self._known())
            if not target:
                continue
            if alias.asname:
                self.s.imports[alias.asname] = (target, None)
            elif "." not in alias.name:
                self.s.imports[alias.name] = (target, None)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        target = _module_rel_for(node.module or "", self.info.path,
                                 node.level or 0, self._known())
        if target is None:
            return
        for alias in node.names:
            # `from pkg import mod` where pkg/mod.py exists binds the
            # MODULE, not an attr of pkg/__init__.py
            dotted = alias.name if not node.module \
                else node.module + "." + alias.name
            sub = _module_rel_for(dotted, self.info.path,
                                  node.level or 0, self._known())
            if sub and sub != target:
                self.s.imports[alias.asname or alias.name] = (sub, None)
            else:
                self.s.imports[alias.asname or alias.name] = \
                    (target, alias.name)

    def _known(self) -> Set[str]:
        return getattr(self, "_known_paths", set())

    # -- loops (lexical, for loop-fetch detection) -------------------
    def _loop(self, node) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _loop
    visit_ListComp = visit_SetComp = visit_DictComp = _loop
    visit_GeneratorExp = _loop

    # -- concurrency facts -------------------------------------------
    def visit_If(self, node: ast.If) -> None:
        # `if not _from_signal:` BODIES are the deferred-flush idiom:
        # signal-safety prunes call edges inside them from the handler
        # closure.  Polarity matters — the guard must be the NEGATION
        # of the flag, and only the body (never the orelse) is blessed:
        # `if _from_signal: flush()` runs the flush IN signal context
        # and must keep flagging.
        if self.fn_stack and isinstance(node.test, ast.UnaryOp) and \
                isinstance(node.test.op, ast.Not) and node.body:
            for sub in ast.walk(node.test.operand):
                ident = sub.id if isinstance(sub, ast.Name) else (
                    sub.attr if isinstance(sub, ast.Attribute) else None)
                if ident and _SIGNAL_FLAG_RE.search(ident):
                    self.fn_stack[-1].deferred_spans.append(
                        (node.body[0].lineno,
                         node.body[-1].end_lineno or
                         node.body[-1].lineno))
                    break
        self.generic_visit(node)

    def _with(self, node) -> None:
        if self.fn_stack:
            for item in node.items:
                lock = lock_id_of(item.context_expr)
                if lock is not None:
                    self.fn_stack[-1].lock_regions.append(
                        (lock, node.lineno,
                         node.end_lineno or node.lineno))
        self.generic_visit(node)

    visit_With = visit_AsyncWith = _with

    def _record_conc_op(self, name: str, node: ast.Call) -> None:
        """Classify one call as a concurrency-relevant operation on the
        enclosing function (caller guarantees ``self.fn_stack``)."""
        fn = self.fn_stack[-1]
        tail = name.rsplit(".", 1)[-1]
        recv = name[: -(len(tail) + 1)] if "." in name else ""
        if name == "open":
            fn.conc_ops.append(("file-io", node.lineno,
                                open_mode(node) or ""))
        elif name == "print" or name.endswith("print_rank") or \
                name.startswith("logging."):
            fn.conc_ops.append(("log", node.lineno, name))
        elif tail in _LOG_LEVEL_TAILS and recv and \
                _LOGGER_RECV_RE.search(recv):
            fn.conc_ops.append(("log", node.lineno, name))
        elif tail == "join" and not node.args:
            # zero-arg `.join()` is a thread/process join; str.join
            # always takes its iterable positionally
            fn.conc_ops.append(("blocking-join", node.lineno, recv))
        elif tail == "wait" and recv:
            lock = recv[5:] if recv.startswith("self.") else recv
            fn.conc_ops.append(("blocking-wait", node.lineno, lock))
        elif name in ("time.sleep", "sleep"):
            fn.conc_ops.append(("blocking-sleep", node.lineno, ""))
        elif tail in ("acquire", "release") and recv and \
                _LOCK_NAME_RE.search(recv.rsplit(".", 1)[-1]):
            # same filter as with-statements: only lock-looking
            # receivers register (`pool_slot.acquire()` is not a lock)
            lock = recv[5:] if recv.startswith("self.") else recv
            fn.conc_ops.append((f"lock-{tail}", node.lineno, lock))

    # -- statements -------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        value = node.value
        if isinstance(value, ast.Call) and \
                call_name(value) in JIT_FACTORIES:
            static = self._static_spec(value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.s.jit_names.append(tgt.id)
                    if static:
                        self.s.static_jit[tgt.id] = static
                elif isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    self.s.jit_attrs.append(tgt.attr)
                    if static:
                        self.s.static_jit["self." + tgt.attr] = static
        kind = spec_kind_of(value)
        if kind is not None:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.s.spec_bindings.append(
                        (tgt.id, kind, node.lineno))
                elif isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    self.s.spec_bindings.append(
                        ("self." + tgt.attr, kind, node.lineno))
        if self.fn_stack:
            for tgt in node.targets:
                self._record_self_write(tgt)
            fn = self.fn_stack[-1]
            for tgt in node.targets:
                # direct `self.X = expr` / `name = expr` bindings carry
                # their value source for the thread-escape snapshot check
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    fn.self_assigns.append(
                        (tgt.attr, node.lineno, self._src_of(value)))
                elif isinstance(tgt, ast.Name):
                    fn.local_assigns[tgt.id] = self._src_of(value)
        self.generic_visit(node)

    @staticmethod
    def _src_of(node: ast.AST, limit: int = 200) -> str:
        try:
            src = ast.unparse(node)
        except Exception:  # pragma: no cover - unparse is total on 3.9+
            return ""
        return src if len(src) <= limit else src[:limit]

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self.fn_stack:
            self._record_self_write(node.target)
        self.generic_visit(node)

    def _record_self_write(self, tgt: ast.AST) -> None:
        """``self.X`` / ``self.X[...]`` / ``self.X.Y`` store targets
        count as writes of attr ``X`` (mutation of its object)."""
        if not isinstance(tgt, (ast.Attribute, ast.Subscript)):
            return
        attr_node = tgt
        while isinstance(attr_node, ast.Subscript):
            attr_node = attr_node.value
        if not isinstance(attr_node, ast.Attribute):
            return
        while isinstance(attr_node.value, (ast.Attribute, ast.Subscript)):
            attr_node = attr_node.value
            while isinstance(attr_node, ast.Subscript):
                attr_node = attr_node.value
            if not isinstance(attr_node, ast.Attribute):
                return
        if isinstance(attr_node.value, ast.Name) and \
                attr_node.value.id == "self":
            self.fn_stack[-1].self_writes.append(attr_node.attr)

    @staticmethod
    def _static_spec(call: ast.Call) -> Optional[Dict[str, Any]]:
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                nums = []
                for elt in (kw.value.elts if isinstance(
                        kw.value, (ast.Tuple, ast.List)) else [kw.value]):
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, int):
                        nums.append(elt.value)
                return {"argnums": nums, "argnames": [],
                        "line": call.lineno}
            if kw.arg == "static_argnames":
                names = []
                for elt in (kw.value.elts if isinstance(
                        kw.value, (ast.Tuple, ast.List)) else [kw.value]):
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        names.append(elt.value)
                return {"argnums": [], "argnames": names,
                        "line": call.lineno}
        return None

    # -- expressions ------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.fn_stack and isinstance(node.ctx, ast.Load) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            self.fn_stack[-1].self_reads.append(node.attr)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # mesh fact layer: a Load of `pool[slot_ids]` is a pool-table
        # gather.  `.at[...]` chains are scatter TARGETS (recorded as
        # drop_scatters in visit_Call), not gathers — a chain through
        # `.at` is skipped.
        if self.fn_stack and isinstance(node.ctx, ast.Load) and \
                not isinstance(node.slice, ast.Constant):
            base = node.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if not (isinstance(base, ast.Attribute) and
                    base.attr == "at"):
                bname = dotted_name(base)
                if pool_name(bname):
                    fn = self.fn_stack[-1]
                    slice_src = self._src_of(node.slice, 80)
                    prov = slice_src
                    if isinstance(node.slice, ast.Name):
                        prov += " " + fn.local_assigns.get(
                            node.slice.id, "")
                    if _SLOT_SLICE_RE.search(prov):
                        fn.slot_gathers.append(
                            (bname.rsplit(".", 1)[-1], slice_src,
                             node.lineno))
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        # telemetry event records built as dict literals ({"kind": ...})
        # — the xla.py drain-queue pattern
        for key, val in zip(node.keys, node.values):
            if isinstance(key, ast.Constant) and key.value == "kind":
                for arm in ([val.body, val.orelse]
                            if isinstance(val, ast.IfExp) else [val]):
                    if isinstance(arm, ast.Constant) and \
                            isinstance(arm.value, str):
                        self.s.events.append(
                            (arm.value, node.lineno, "kind-literal"))
        self.generic_visit(node)

    def _event_name(self, arg: ast.AST) -> Optional[str]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.JoinedStr) and arg.values and \
                isinstance(arg.values[0], ast.Constant):
            return str(arg.values[0].value) + "*"
        return None

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        if name is not None and self.fn_stack:
            self.fn_stack[-1].calls.append((name, node.lineno))
            self._record_conc_op(name, node)
        if name in _THREAD_FACTORIES:
            target = ""
            named = False
            for kw in node.keywords:
                if kw.arg == "target":
                    target = dotted_name(kw.value) or ""
                elif kw.arg == "name":
                    named = True
            self.s.thread_spawns.append(
                (target, node.lineno, named,
                 self.class_stack[-1] if self.class_stack else None,
                 self.fn_stack[-1].qual if self.fn_stack else ""))
        if name == "signal.signal" and len(node.args) >= 2:
            handler = dotted_name(node.args[1])
            if handler:
                self.s.signal_handlers.append(
                    (handler, node.lineno,
                     self.class_stack[-1] if self.class_stack else None))
        if name in _DEVGET_NAMES and self.fn_stack:
            arg_src = ast.unparse(node.args[0]) if node.args else ""
            self.fn_stack[-1].device_gets.append(
                (node.lineno, arg_src, self.loop_depth > 0))
        # trace roots from named function args (incl. functools.partial)
        if name in TRACE_ENTRY:
            cls = self.class_stack[-1] if self.class_stack else None
            for arg in node.args:
                ref = dotted_name(arg)
                if ref is None and isinstance(arg, ast.Call) and \
                        call_name(arg) in ("functools.partial", "partial"):
                    ref = arg.args and dotted_name(arg.args[0]) or None
                if not ref:
                    continue
                self.s.traced_roots.append((ref, cls))
                # mesh fact layer: the lane/shard_map split rides along
                # (shard-locality prohibits collectives in the former
                # and audits pool gathers in the latter)
                if name in _LANE_ENTRIES:
                    self.s.lane_roots.append(
                        (ref, cls,
                         self.fn_stack[-1].qual if self.fn_stack
                         else ""))
                elif name in _SHARD_MAP_ENTRIES:
                    self.s.shardmap_roots.append(
                        (ref, cls,
                         self.fn_stack[-1].qual if self.fn_stack
                         else "", node.lineno))
        # telemetry emissions
        tail = name.rsplit(".", 1)[-1] if name else None
        # -- mesh fact layer -------------------------------------------
        if self.fn_stack and tail in COLLECTIVE_OPS:
            axis: Optional[ast.AST] = \
                node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis = kw.value
            self.fn_stack[-1].collectives.append(
                (tail, node.lineno, axis_desc_of(axis)))
        elif self.fn_stack and tail == "axis_index":
            axis = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis = kw.value
            self.fn_stack[-1].collectives.append(
                ("axis_index", node.lineno, axis_desc_of(axis)))
        if tail in _PARTITION_SPEC_TAILS:
            for arg in node.args:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    self.s.spec_literals.append(
                        (arg.value, node.lineno))
        if name in _DEVICE_PUT_NAMES and node.args:
            spec: Optional[ast.AST] = \
                node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg in ("device", "sharding"):
                    spec = kw.value
            if spec is None:
                desc = "none"
            else:
                desc = spec_kind_of(spec)
                if desc is None:
                    dn = dotted_name(spec)
                    desc = f"name:{dn}" if dn else "dynamic"
            self.s.device_puts.append(
                (self._src_of(node.args[0], 80), desc, node.lineno,
                 self.fn_stack[-1].qual if self.fn_stack else ""))
        if self.fn_stack and isinstance(node.func, ast.Attribute) and \
                node.func.attr == "set":
            # `pool.at[slots].set(rows, mode="drop")` — the donated
            # fixed-shape page-in scatter
            mode = next((kw.value for kw in node.keywords
                         if kw.arg == "mode"), None)
            recv = node.func.value
            if isinstance(mode, ast.Constant) and mode.value == "drop" \
                    and isinstance(recv, ast.Subscript) and \
                    isinstance(recv.value, ast.Attribute) and \
                    recv.value.attr == "at":
                base = recv.value.value
                while isinstance(base, ast.Subscript):
                    base = base.value
                bname = dotted_name(base)
                if pool_name(bname):
                    self.fn_stack[-1].drop_scatters.append(
                        (bname.rsplit(".", 1)[-1], node.lineno))
        if tail in _EVENT_APIS:
            idx = _EVENT_APIS[tail]
            if len(node.args) > idx:
                ev = self._event_name(node.args[idx])
                if ev:
                    self.s.events.append((ev, node.lineno, tail))
        elif name and name.endswith(".event") and node.args:
            ev = self._event_name(node.args[0])
            if ev:
                self.s.events.append((ev, node.lineno, "event"))
        elif name and name.endswith("on_event") and node.args:
            ev = self._event_name(node.args[0])
            if ev:
                self.s.events.append((ev, node.lineno, "event"))
        if name and node.args:
            if name.endswith(".publish"):
                ev = self._event_name(node.args[0])
                if ev:
                    self.s.devbus.append((ev, node.lineno, "publish"))
            elif name.endswith("devbus_host"):
                ev = self._event_name(node.args[0])
                if ev:
                    self.s.devbus.append((ev, node.lineno,
                                          "devbus_host"))
        self.generic_visit(node)


def compute_module_summary(info: ModuleInfo,
                           known_paths: Optional[Set[str]] = None
                           ) -> ModuleSummary:
    """Extract ``info``'s :class:`ModuleSummary` (one AST walk)."""
    summary = ModuleSummary(info.path)
    visitor = _SummaryVisitor(info, summary)
    visitor._known_paths = known_paths or set()
    visitor.visit(info.tree)
    return summary


#: in-process summary cache: abspath -> (mtime_ns, size, summary).
#: Shared across analyze() calls so the tier-1 gate and the test suite
#: never re-summarize an unchanged file twice in one process.
_SUMMARY_CACHE: Dict[str, Tuple[int, int, ModuleSummary]] = {}


def _file_stamp(abspath: str) -> Tuple[int, int]:
    st = os.stat(abspath)
    return (st.st_mtime_ns, st.st_size)


class Project:
    """The project-wide call graph + reachability queries."""

    def __init__(self, root: str,
                 modules: Dict[str, ModuleSummary]):
        self.root = root
        self.modules = modules
        self._traced: Optional[Set[Tuple[str, str]]] = None

    # -- resolution --------------------------------------------------
    def resolve(self, module: str, ref: str,
                cls: Optional[str] = None
                ) -> Optional[Tuple[str, str]]:
        """Resolve a call/ref string written in ``module`` (optionally
        inside class ``cls``) to a ``(module, qual)`` function, or None
        when it points outside the project / cannot be proven."""
        mod = self.modules.get(module)
        if mod is None:
            return None
        if ref.startswith("self."):
            attr = ref.split(".", 1)[1]
            if "." in attr:
                return None  # self.a.b: attribute-of-attribute dispatch
            return self._resolve_method(module, cls, attr, set())
        if "." not in ref:
            qual = mod.name_index.get(ref)
            if qual is not None:
                return (module, qual)
            imp = mod.imports.get(ref)
            if imp is not None and imp[1] is not None:
                target_mod = self.modules.get(imp[0])
                if target_mod is not None:
                    qual = target_mod.name_index.get(imp[1])
                    if qual is not None:
                        return (imp[0], qual)
            return None
        head, rest = ref.split(".", 1)
        imp = mod.imports.get(head)
        if imp is not None and imp[1] is None and "." not in rest:
            target_mod = self.modules.get(imp[0])
            if target_mod is not None:
                qual = target_mod.name_index.get(rest)
                if qual is not None:
                    return (imp[0], qual)
        return None

    def _resolve_method(self, module: str, cls: Optional[str],
                        attr: str, seen: Set[Tuple[str, str]]
                        ) -> Optional[Tuple[str, str]]:
        """``self.attr`` -> the method, walking same-named base classes
        (resolved through imports) with a cycle guard."""
        if cls is None or (module, cls) in seen:
            return None
        seen.add((module, cls))
        mod = self.modules.get(module)
        if mod is None:
            return None
        qual = f"{cls}.{attr}"
        if qual in mod.functions:
            return (module, qual)
        for base in mod.class_bases.get(cls, []):
            base_name = base.rsplit(".", 1)[-1]
            if base_name in mod.class_bases or \
                    any(q.startswith(base_name + ".")
                        for q in mod.functions):
                found = self._resolve_method(module, base_name, attr,
                                             seen)
                if found:
                    return found
            imp = mod.imports.get(base.split(".")[0])
            if imp is not None:
                # both `from .base import BaseStrategy` (attr import)
                # and `from . import base` + `base.BaseStrategy`
                # (module import) resolve the base's METHODS in imp[0]
                found = self._resolve_method(imp[0], base_name, attr,
                                             seen)
                if found:
                    return found
        return None

    def function(self, key: Tuple[str, str]) -> Optional[FunctionSummary]:
        mod = self.modules.get(key[0])
        return mod.functions.get(key[1]) if mod else None

    # -- jitted bindings ---------------------------------------------
    def imported_jit_names(self, module: str) -> Set[str]:
        """Local names of ``module`` that are module-level jit-factory
        bindings in their DEFINING module — the cross-module half of
        host-sync's taint seeding."""
        mod = self.modules.get(module)
        if mod is None:
            return set()
        out: Set[str] = set()
        for local, (target, attr) in mod.imports.items():
            if attr is None:
                continue
            target_mod = self.modules.get(target)
            if target_mod is not None and attr in target_mod.jit_names:
                out.add(local)
        return out

    # -- trace-context closure ---------------------------------------
    def traced_reachable(self) -> Set[Tuple[str, str]]:
        """Every function that runs INSIDE a trace: named roots handed
        to jit/vmap/scan/... (including ``self._fn = jax.jit(body)``
        method bindings and decorator form), closed over the project
        call graph.  Cycles are fine (seen-set)."""
        if self._traced is not None:
            return self._traced
        frontier: List[Tuple[str, str]] = []
        for path, mod in self.modules.items():
            for ref, cls in mod.traced_roots:
                resolved = self.resolve(path, ref, cls)
                if resolved:
                    frontier.append(resolved)
        seen: Set[Tuple[str, str]] = set()
        while frontier:
            key = frontier.pop()
            if key in seen:
                continue
            seen.add(key)
            fn = self.function(key)
            if fn is None:
                continue
            for ref, _line in fn.calls:
                callee = self.resolve(key[0], ref, fn.cls)
                if callee and callee not in seen:
                    frontier.append(callee)
        self._traced = seen
        return seen

    # -- round-path closure (transfer-budget, signal-safety, ...) ----
    def reachable_from(self, roots: Iterable[Tuple[str, str]],
                       stop: Optional[re.Pattern] = None,
                       skip_edge: Optional[Any] = None
                       ) -> Dict[Tuple[str, str], Tuple[str, str]]:
        """BFS closure over the host call graph from ``roots``; returns
        ``{function: caller}`` back-edges (roots map to themselves).
        ``stop`` prunes callees whose BARE NAME matches (cadence
        boundaries: eval/checkpoint-class functions).  ``skip_edge`` is
        an optional ``(caller FunctionSummary, call line) -> bool``
        predicate pruning individual call edges (signal-safety's
        deferred-flush spans) — ONE closure walk serves every checker,
        so resolution improvements can never make them disagree."""
        parents: Dict[Tuple[str, str], Tuple[str, str]] = {}
        frontier = []
        for key in roots:
            if key not in parents:
                parents[key] = key
                frontier.append(key)
        while frontier:
            key = frontier.pop()
            fn = self.function(key)
            if fn is None:
                continue
            for ref, line in fn.calls:
                if skip_edge is not None and skip_edge(fn, line):
                    continue
                callee = self.resolve(key[0], ref, fn.cls)
                if callee is None or callee in parents:
                    continue
                callee_fn = self.function(callee)
                if callee_fn is None:
                    continue
                if stop is not None and stop.search(callee_fn.name):
                    continue
                parents[callee] = key
                frontier.append(callee)
        return parents

    def call_path(self, parents: Dict[Tuple[str, str], Tuple[str, str]],
                  key: Tuple[str, str]) -> List[str]:
        """Human-readable root -> ... -> key chain from a
        :meth:`reachable_from` result."""
        chain = [key]
        while parents.get(chain[-1]) not in (None, chain[-1]):
            chain.append(parents[chain[-1]])
        return [f"{m}::{q}" for m, q in reversed(chain)]


def build_project(root: str, project_files: List[str],
                  infos: Optional[Dict[str, ModuleInfo]] = None,
                  cache: Optional[Dict[str, Any]] = None) -> Project:
    """Summarize ``project_files`` (abs paths) into a :class:`Project`.

    ``infos`` carries already-parsed modules (the analyzed set) so no
    file is parsed twice.  ``cache`` is an optional disk-cache dict (see
    :func:`load_summary_cache`): entries whose (mtime_ns, size) stamp
    still matches are reused WITHOUT re-reading the file — the
    ``--changed`` incremental contract."""
    known = {os.path.relpath(p, root).replace(os.sep, "/")
             for p in project_files}
    modules: Dict[str, ModuleSummary] = {}
    for abspath in project_files:
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            stamp = _file_stamp(abspath)
        except OSError:
            continue
        hit = _SUMMARY_CACHE.get(abspath)
        if hit is not None and (hit[0], hit[1]) == stamp:
            modules[rel] = hit[2]
            continue
        if cache is not None:
            entry = cache.get(rel)
            if entry is not None and \
                    tuple(entry.get("stamp", ())) == stamp:
                summary = ModuleSummary.from_dict(entry["summary"])
                modules[rel] = summary
                _SUMMARY_CACHE[abspath] = (stamp[0], stamp[1], summary)
                continue
        info = infos.get(rel) if infos else None
        if info is None:
            info = load_module(abspath, root)
        if getattr(info, "parse_error", None) is not None:
            continue
        summary = compute_module_summary(info, known)
        modules[rel] = summary
        _SUMMARY_CACHE[abspath] = (stamp[0], stamp[1], summary)
        if cache is not None:
            cache[rel] = {"stamp": list(stamp),
                          "summary": summary.to_dict()}
    return Project(os.path.abspath(root), modules)


# ----------------------------------------------------------------------
# disk summary cache (tools/flint --changed)
# ----------------------------------------------------------------------
_CACHE_VERSION = 1

#: version of the SUMMARY EXTRACTOR's output shape.  Disk-cache entries
#: are keyed by (mtime_ns, size) — stamps that do not change when the
#: ANALYZER changes — so without this key a new PR's extractor could be
#: served stale summaries missing its new fact fields and silently
#: report nothing.  Bump it whenever ModuleSummary/FunctionSummary gain,
#: lose or reinterpret a field; a mismatch discards the cache wholesale.
#: History: 1 = flint v2 (PR 9); 2 = concurrency fact layer
#: (lock regions, conc ops, thread spawns, signal handlers, assigns);
#: 3 = mesh fact layer (collectives, slot gathers/scatters, lane and
#: shard_map roots, sharding-spec bindings, device_put sites).
SUMMARY_SCHEMA_VERSION = 3


def default_cache_path(root: str) -> str:
    return os.path.join(root, ".flint_cache.json")


def load_summary_cache(path: str,
                       root: Optional[str] = None) -> Dict[str, Any]:
    """Entries are keyed by ROOT-relative path and their summaries
    carry root-relative module paths, so a cache warmed under a
    different analysis root must be discarded wholesale — reusing it
    would report findings at the wrong paths."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            raw = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return {}
    if raw.get("version") != _CACHE_VERSION:
        return {}
    if raw.get("schema") != SUMMARY_SCHEMA_VERSION:
        return {}  # summaries written by a different extractor: recompute
    if root is not None and raw.get("root") not in (None,
                                                   os.path.abspath(root)):
        return {}
    entries = raw.get("entries")
    return entries if isinstance(entries, dict) else {}


def save_summary_cache(path: str, cache: Dict[str, Any],
                       root: Optional[str] = None) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"version": _CACHE_VERSION,
                   "schema": SUMMARY_SCHEMA_VERSION,
                   "root": os.path.abspath(root) if root else None,
                   "entries": cache}, fh)
    os.replace(tmp, path)


def function_nodes(info: ModuleInfo) -> Dict[str, ast.AST]:
    """AST def nodes of ``info`` keyed by the SAME qualnames the
    summary extractor assigns — the bridge from a reachability answer
    back to a body to walk.  Memoized on the info (three checkers ask
    per file)."""
    cached = getattr(info, "_fn_nodes", None)
    if cached is not None:
        return cached
    out: Dict[str, ast.AST] = {}

    def walk(node: ast.AST, prefix: str, in_fn: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, prefix if in_fn else prefix + child.name + ".",
                     in_fn)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = prefix + child.name
                out[qual] = child
                walk(child, qual + ".", True)
            else:
                walk(child, prefix, in_fn)

    walk(info.tree, "", False)
    info._fn_nodes = out  # type: ignore[attr-defined]
    return out


# ----------------------------------------------------------------------
# runner
# ----------------------------------------------------------------------
def _iter_py_files(paths: List[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(os.path.abspath(p))
        elif os.path.isdir(p):
            for base, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for name in sorted(names):
                    if name.endswith(".py"):
                        files.append(os.path.abspath(
                            os.path.join(base, name)))
    return sorted(set(files))


def load_module(abspath: str, root: str) -> ModuleInfo:
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    with open(abspath, "r", encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=abspath)
    except SyntaxError as exc:
        info = ModuleInfo(rel, abspath, src, ast.Module(body=[],
                                                        type_ignores=[]),
                          src.splitlines())
        info.parse_error = exc  # type: ignore[attr-defined]
        return info
    return ModuleInfo(rel, abspath, src, tree, src.splitlines())


def analyze(paths: List[str], root: Optional[str] = None,
            rules: Optional[Set[str]] = None,
            project_paths: Optional[List[str]] = None,
            cache: Optional[Dict[str, Any]] = None,
            with_project_checkers: bool = True) -> List[Finding]:
    """Run every checker over ``paths``; returns suppression-filtered
    findings (baseline NOT applied — that is the caller's policy).

    ``project_paths`` widens the CALL-GRAPH scope beyond the analyzed
    set (``--changed`` analyzes the edited files against the whole
    package's summaries); findings are only emitted for ``paths``.
    ``cache`` is a disk-cache dict (:func:`load_summary_cache`) updated
    in place.  ``with_project_checkers=False`` skips the project-level
    checkers (schema-drift, guard-matrix, event-schema,
    transfer-budget) — the incremental mode's call when none of their
    inputs changed."""
    from . import (atomic_write, collective_budget, donation,
                   event_schema, guard_matrix, host_sync, jit_purity,
                   lock_discipline, mesh_axis, pallas_shape, put_loop,
                   recompile_hazard, schema_drift, shard_locality,
                   shard_ready, signal_safety, spec_drift,
                   thread_escape, transfer_budget)

    root = os.path.abspath(root or os.getcwd())
    files = _iter_py_files(paths)
    proj_files = sorted(set(files) | set(
        _iter_py_files(project_paths or [])))

    # parse the analyzed set once; summaries for the rest come from the
    # caches (or a fresh parse on a cold run)
    infos: Dict[str, ModuleInfo] = {}
    findings: List[Finding] = []
    suppressions: List[Suppression] = []
    analyzed_rel: Set[str] = set()
    for abspath in files:
        info = load_module(abspath, root)
        analyzed_rel.add(info.path)
        if getattr(info, "parse_error", None) is not None:
            exc = info.parse_error  # type: ignore[attr-defined]
            findings.append(Finding("parse-error", info.path,
                                    exc.lineno or 1, str(exc.msg)))
            continue
        infos[info.path] = info
        suppressions.extend(parse_suppressions(info))

    project = build_project(root, proj_files, infos=infos, cache=cache)

    # project-level findings can land in files OUTSIDE the analyzed set
    # (a transfer-budget finding in an unchanged engine file whose
    # round path a changed helper joined; an event-schema finding in a
    # telemetry module a subset run never named) — their pragmas must
    # still suppress, so parse the WHOLE package's pragmas too, out of
    # hygiene scope
    if with_project_checkers:
        pragma_files = set(proj_files)
        pkg_dir = os.path.join(root, "msrflute_tpu")
        if os.path.isdir(pkg_dir):
            pragma_files |= set(_iter_py_files([pkg_dir]))
        for abspath in sorted(pragma_files):
            rel = os.path.relpath(abspath, root).replace(os.sep, "/")
            if rel in analyzed_rel:
                continue
            info = load_module(abspath, root)
            if getattr(info, "parse_error", None) is not None:
                continue
            for sup in parse_suppressions(info):
                sup.in_scope = False
                suppressions.append(sup)

    per_file_checkers = [
        (host_sync.RULE, lambda i: host_sync.check(i, project)),
        (donation.RULE, donation.check),
        (jit_purity.RULE, lambda i: jit_purity.check(i, project)),
        (pallas_shape.RULE, pallas_shape.check),
        (put_loop.RULE, put_loop.check),
        (shard_ready.RULE, lambda i: shard_ready.check(i, project)),
        (recompile_hazard.RULE,
         lambda i: recompile_hazard.check(i, project)),
        (atomic_write.RULE, atomic_write.check),
        (mesh_axis.RULE, lambda i: mesh_axis.check(i, project)),
        (spec_drift.RULE, lambda i: spec_drift.check(i, project)),
    ]
    for rel in sorted(infos):
        info = infos[rel]
        for rule, check in per_file_checkers:
            if rules and rule not in rules:
                continue
            findings.extend(check(info))

    if with_project_checkers:
        if rules is None or transfer_budget.RULE in rules:
            findings.extend(transfer_budget.check_project(
                project, emit_paths=analyzed_rel
                if project_paths else None))
        if rules is None or schema_drift.RULE in rules:
            findings.extend(schema_drift.check_project(root))
        if rules is None or guard_matrix.RULE in rules:
            findings.extend(guard_matrix.check_project(
                root, trees={rel: i.tree for rel, i in infos.items()}))
        if rules is None or event_schema.RULE in rules:
            findings.extend(event_schema.check_project(
                root, modules=project.modules))
        emit = analyzed_rel if project_paths else None
        if rules is None or signal_safety.RULE in rules:
            findings.extend(signal_safety.check_project(
                project, emit_paths=emit))
        if rules is None or lock_discipline.RULE in rules:
            findings.extend(lock_discipline.check_project(
                project, emit_paths=emit))
        if rules is None or thread_escape.RULE in rules:
            findings.extend(thread_escape.check_project(
                project, emit_paths=emit))
        if rules is None or shard_locality.RULE in rules:
            findings.extend(shard_locality.check_project(
                project, emit_paths=emit))
        if rules is None or collective_budget.RULE in rules:
            findings.extend(collective_budget.check_project(
                root, project))
        # project-checker findings live in .py/.md files that may carry
        # inline pragmas; .md pragmas are not a thing, which is fine
        # because the actionable end of a doc drift is the doc itself.

    # staleness is judged only for rules that RAN AND APPLIED: a
    # doc-vs-code checker that returned early (tree without its doc /
    # schema inputs, or a --changed run that skipped project checkers)
    # must not mark its pragmas stale
    active = set(rules) if rules is not None else set(RULES)
    project_rules = {transfer_budget.RULE, schema_drift.RULE,
                     guard_matrix.RULE, event_schema.RULE,
                     signal_safety.RULE, lock_discipline.RULE,
                     thread_escape.RULE, shard_locality.RULE,
                     collective_budget.RULE}
    if not with_project_checkers:
        active -= project_rules
    else:
        pkg = os.path.join(root, "msrflute_tpu")
        if not (os.path.exists(os.path.join(pkg, "schema.py")) and
                os.path.exists(os.path.join(pkg, "config.py"))):
            active.discard(schema_drift.RULE)
        if not (os.path.exists(os.path.join(pkg, "engine", "server.py"))
                and os.path.exists(os.path.join(pkg, "schema.py"))):
            active.discard(guard_matrix.RULE)
        if not os.path.exists(os.path.join(root, "docs",
                                           "observability.md")):
            active.discard(event_schema.RULE)
        if not os.path.exists(os.path.join(root, "docs",
                                           "architecture.md")):
            active.discard(collective_budget.RULE)
    return apply_suppressions(findings, suppressions,
                              active_rules=active)
