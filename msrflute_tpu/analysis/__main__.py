"""fluteguard CLI: ``python -m msrflute_tpu.analysis [paths]``.

Exit codes: 0 clean (after baseline), 1 findings, 2 usage error.

Incremental mode (``--changed``) analyzes only the files git reports as
modified (staged, unstaged and untracked vs HEAD, or vs ``--changed
BASE``) while the interprocedural call graph still spans the whole
package — unchanged files contribute their summaries from the on-disk
cache (``.flint_cache.json``, mtime-keyed) without being re-parsed.
Project-level checkers (schema-drift, guard-matrix, event-schema,
transfer-budget) run only when one of their inputs changed (any doc,
schema/config, or a hot-path module).

Machine output: ``--format json`` (one object per finding with a
stable ``id``) or ``--format sarif`` (SARIF 2.1.0 for editor/CI
ingestion; the finding id rides ``partialFingerprints``).  IDs hash the
line-free baseline key, so they survive unrelated edits.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from . import RULES
from .core import (Finding, analyze, default_baseline_path,
                   default_cache_path, filter_baseline, load_baseline,
                   load_summary_cache, save_summary_cache,
                   write_baseline)

#: file classes whose change triggers the project-level checkers in
#: --changed mode (their inputs: docs, schema/config, hot-path modules,
#: and — for the concurrency rules — anywhere threads/locks/handlers
#: or durable writes live)
_PROJECT_TRIGGER_PARTS = ("docs/", "README.md", "schema.py", "config.py",
                          "engine/", "strategies/", "ops/", "telemetry/",
                          "robust/", "resilience/", "analysis/",
                          "data/", "rl/", "utils/", "parallel/")


def _git_changed_files(root: str, base: Optional[str]
                       ) -> "tuple[str, List[str]]":
    """``(toplevel, changed)``: the repo toplevel plus changed +
    untracked files vs HEAD (or the MERGE BASE with ``base``), as
    ABSOLUTE paths.  git prints paths relative to the repo TOPLEVEL
    (not the cwd/--root), so they are resolved against ``rev-parse
    --show-toplevel`` — running from a subdirectory must not silently
    lint nothing.  An explicit base compares against ``git merge-base
    base HEAD`` (the documented 'what did THIS branch change'
    semantics), not base's tip — otherwise commits that landed on base
    after the branch point would all read as changed here."""
    def run(*cmd: str) -> str:
        proc = subprocess.run(["git", "-C", root, *cmd],
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip() or
                               f"git {' '.join(cmd)} failed")
        return proc.stdout
    toplevel = run("rev-parse", "--show-toplevel").strip()
    diff_base = "HEAD" if base is None \
        else run("merge-base", base, "HEAD").strip()
    out: List[str] = []
    for text in (run("diff", "--name-only", diff_base),
                 run("ls-files", "--others", "--exclude-standard",
                     "--full-name")):
        out.extend(os.path.join(toplevel, line.strip())
                   for line in text.splitlines() if line.strip())
    return toplevel, sorted(set(out))


def _to_json(findings: List[Finding]) -> str:
    return json.dumps(
        [{"id": f.id, "rule": f.rule, "path": f.path, "line": f.line,
          "message": f.message, "hint": f.hint} for f in findings],
        indent=2)


def _to_sarif(findings: List[Finding]) -> str:
    rules = sorted({f.rule for f in findings} | set(RULES))
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message +
                        (f"\nhint: {f.hint}" if f.hint else "")},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                }}],
            "partialFingerprints": {"flintFindingId/v1": f.id},
        })
    sarif = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "fluteguard",
                "informationUri":
                    "docs/static_analysis.md",
                "rules": [{"id": r} for r in rules],
            }},
            "results": results,
        }],
    }
    return json.dumps(sarif, indent=2)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="flint",
        description="fluteguard — TPU-safety static analysis "
                    "(host-sync, donation-aliasing, jit-purity, "
                    "pallas-shape, put-loop, schema-drift, shard-ready, "
                    "recompile-hazard, transfer-budget, guard-matrix, "
                    "event-schema, signal-safety, lock-discipline, "
                    "thread-escape, atomic-write, mesh-axis, "
                    "shard-locality, spec-drift, collective-budget)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/dirs to analyze (default: the "
                             "msrflute_tpu package)")
    parser.add_argument("--root", default=None,
                        help="path findings are reported relative to "
                             "(default: cwd)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: the packaged "
                             "analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baseline ignored")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset to run")
    parser.add_argument("--changed", nargs="?", const="HEAD",
                        default=None, metavar="BASE",
                        help="incremental mode: analyze only files git "
                             "reports changed vs BASE (default HEAD) + "
                             "untracked, sharing cached summaries for "
                             "the rest of the package")
    parser.add_argument("--cache", default=None,
                        help="summary-cache path (default: "
                             "<root>/.flint_cache.json; used by "
                             "--changed)")
    parser.add_argument("--format", default=None, dest="fmt",
                        choices=("text", "json", "sarif"),
                        help="output format (default text)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="alias for --format json")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        print("\n".join(RULES))
        return 0
    fmt = args.fmt or ("json" if args.as_json else "text")

    root = os.path.abspath(args.root or os.getcwd())
    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"unknown rules: {sorted(unknown)}", file=sys.stderr)
            return 2

    if args.changed is not None:
        try:
            toplevel, changed = _git_changed_files(
                root, None if args.changed == "HEAD" else args.changed)
        except (OSError, RuntimeError) as exc:
            print(f"flint --changed: {exc}", file=sys.stderr)
            return 2
        norm_paths = [os.path.abspath(p) for p in paths]

        def in_scope(p: str) -> bool:
            for np in norm_paths:
                if os.path.isdir(np):
                    if os.path.commonpath([p, np]) == np:
                        return True
                elif p == np:
                    return True
            return False

        changed_py = [p for p in changed
                      if p.endswith(".py") and os.path.exists(p) and
                      in_scope(p)]
        rel_changed = [os.path.relpath(c, root).replace(os.sep, "/")
                       for c in changed]
        with_project = any(part in c for c in rel_changed
                           for part in _PROJECT_TRIGGER_PARTS)
        # the cache lives at the repo TOPLEVEL (where .gitignore covers
        # it) but is ROOT-scoped: entries carry root-relative paths, so
        # a cache warmed under a different --root/cwd is discarded
        cache_path = args.cache or default_cache_path(toplevel)
        cache = load_summary_cache(cache_path, root=root)
        findings = analyze(changed_py, root=root, rules=rules,
                           project_paths=paths, cache=cache,
                           with_project_checkers=with_project)
        try:
            save_summary_cache(cache_path, cache, root=root)
        except OSError:
            pass  # a read-only checkout still lints, just cold
    else:
        findings = analyze(paths, root=root, rules=rules)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0
    if not args.no_baseline:
        findings = filter_baseline(findings, load_baseline(baseline_path))

    if fmt == "json":
        print(_to_json(findings))
    elif fmt == "sarif":
        print(_to_sarif(findings))
    else:
        for f in findings:
            print(f.render())
        print(f"fluteguard: {len(findings)} finding(s)"
              + ("" if args.no_baseline else " (after baseline)"))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
