"""fluteguard CLI: ``python -m msrflute_tpu.analysis [paths]``.

Exit codes: 0 clean (after baseline), 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import RULES
from .core import (analyze, default_baseline_path, filter_baseline,
                   load_baseline, write_baseline)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="flint",
        description="fluteguard — TPU-safety static analysis "
                    "(host-sync, donation-aliasing, jit-purity, "
                    "pallas-shape, schema-drift)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/dirs to analyze (default: the "
                             "msrflute_tpu package)")
    parser.add_argument("--root", default=None,
                        help="path findings are reported relative to "
                             "(default: cwd)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: the packaged "
                             "analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baseline ignored")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset to run")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        print("\n".join(RULES))
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            print(f"unknown rules: {sorted(unknown)}", file=sys.stderr)
            return 2

    findings = analyze(paths, root=root, rules=rules)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0
    if not args.no_baseline:
        findings = filter_baseline(findings, load_baseline(baseline_path))

    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"fluteguard: {len(findings)} finding(s)"
              + ("" if args.no_baseline else " (after baseline)"))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
