"""recompile-hazard — static counterpart of the PR 7 runtime sentinel.

The device-truth layer (``telemetry/xla.py``) catches recompiles when
they HAPPEN: every post-warmup compile is an event with the leaf-level
shape diff.  This rule catches the three code shapes that cause them,
before a chip ever runs:

- **static-arg hazard** — a ``jax.jit(..., static_argnums=/argnames=)``
  binding whose call site passes a DATA-DERIVED value (``len(...)``,
  ``.shape[...]``, arithmetic on them, or an enclosing loop variable)
  in a static position: the static-arg value set is unbounded, so XLA
  compiles one program per distinct value;
- **mutable-capture hazard** — a traced body reads ``self.X`` while a
  host-side method of the same class MUTATES ``self.X``: the traced
  read is baked at trace time, so the mutation either silently never
  reaches the compiled program or (for shape-bearing state) forces a
  retrace per mutation;
- **shape-derived operand hazard** — an array built with a
  data-dependent length (``np.zeros((len(xs), ...))``,
  ``np.empty(n, ...)`` with ``n`` shape-derived) passed DIRECTLY to a
  jitted call: every distinct length is a new compiled program.  Round
  operands must come from the closed bucket set (pad to a static
  capacity), which is exactly what the cohort-bucketing machinery
  exists for.

Scope: hot-path modules.  Traced-body facts and jitted bindings come
from the project summaries, so ``self._fn = jax.jit(...)`` method
dispatch and cross-module imports are covered.  A deliberately small
static-arg domain (a config-time constant, a bool flag) takes an
inline ``# flint: disable=recompile-hazard <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (Finding, ModuleInfo, Project, call_name,
                   dotted_name, function_nodes)

RULE = "recompile-hazard"

_ARRAY_CTORS = {"np.zeros", "np.empty", "np.full", "np.ones",
                "numpy.zeros", "numpy.empty", "numpy.full", "numpy.ones",
                "jnp.zeros", "jnp.empty", "jnp.full", "jnp.ones"}

#: self attrs whose mutation is bookkeeping, not program state — the
#: always-on compile log class of counters
_CAPTURE_EXEMPT_PREFIXES = ("_",)


def _is_data_derived(node: ast.AST, derived: Set[str],
                     loop_vars: Set[str]) -> bool:
    """Whether an expression's value varies with data: contains a
    ``len()`` call, a ``.shape`` read, a name locally bound from one,
    or an enclosing loop variable."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and call_name(sub) == "len":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "shape":
            return True
        if isinstance(sub, ast.Name) and (sub.id in derived or
                                          sub.id in loop_vars):
            return True
    return False


class _HazardWalk(ast.NodeVisitor):
    """One function scope: track shape-derived names + loop vars, flag
    hazardous jitted call sites."""

    def __init__(self, info: ModuleInfo, static_jit: Dict[str, Dict],
                 jit_callables: Set[str], findings: List[Finding]):
        self.info = info
        self.static_jit = static_jit
        self.jit_callables = jit_callables
        self.findings = findings
        self.derived: Set[str] = set()
        self.loop_vars: Set[str] = set()

    def visit_FunctionDef(self, node) -> None:
        pass  # nested scopes walk on their own

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        if _is_data_derived(node.value, self.derived, self.loop_vars):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.derived.add(tgt.id)
        else:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.derived.discard(tgt.id)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        var = node.target.id if isinstance(node.target, ast.Name) else None
        if var:
            self.loop_vars.add(var)
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        if var:
            self.loop_vars.discard(var)

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if name is not None:
            spec = self.static_jit.get(name)
            if spec is not None:
                self._check_static_args(node, name, spec)
            if name in self.jit_callables or spec is not None:
                self._check_operand_shapes(node, name)
        self.generic_visit(node)

    def _check_static_args(self, node: ast.Call, name: str,
                           spec: Dict) -> None:
        for pos in spec.get("argnums", []):
            if pos < len(node.args) and _is_data_derived(
                    node.args[pos], self.derived, self.loop_vars):
                self.findings.append(Finding(
                    RULE, self.info.path, node.lineno,
                    f"data-derived value "
                    f"`{ast.unparse(node.args[pos])}` in static arg "
                    f"{pos} of `{name}` — one XLA compile per distinct "
                    "value",
                    hint="static args must range over a small closed "
                         "set (config constants); pass data as a "
                         "traced operand or pad to a static capacity"))
        for kw in node.keywords:
            if kw.arg in spec.get("argnames", []) and _is_data_derived(
                    kw.value, self.derived, self.loop_vars):
                self.findings.append(Finding(
                    RULE, self.info.path, node.lineno,
                    f"data-derived value `{ast.unparse(kw.value)}` in "
                    f"static arg `{kw.arg}` of `{name}` — one XLA "
                    "compile per distinct value",
                    hint="static args must range over a small closed "
                         "set (config constants); pass data as a "
                         "traced operand or pad to a static capacity"))

    def _check_operand_shapes(self, node: ast.Call, name: str) -> None:
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Call) and \
                    call_name(arg) in _ARRAY_CTORS and arg.args and \
                    _is_data_derived(arg.args[0], self.derived,
                                     self.loop_vars):
                self.findings.append(Finding(
                    RULE, self.info.path, node.lineno,
                    f"operand `{ast.unparse(arg)}` of jitted `{name}` "
                    "has a data-dependent shape — every distinct "
                    "length compiles a new program",
                    hint="pad to a static capacity from the closed "
                         "bucket set (data/batching.py) so the "
                         "compiled-shape set stays closed"))


def _mutable_capture(info: ModuleInfo, project: Project,
                     findings: List[Finding]) -> None:
    mod = project.modules.get(info.path)
    if mod is None:
        return
    traced = {q for (m, q) in project.traced_reachable()
              if m == info.path}
    if not traced:
        return
    # class -> attrs mutated by HOST-side methods (not __init__, not
    # traced, not private bookkeeping)
    writes: Dict[str, Dict[str, str]] = {}
    for qual, fn in mod.functions.items():
        if fn.cls is None or qual in traced or fn.name == "__init__" or \
                fn.name.startswith("_build"):
            continue
        for attr in fn.self_writes:
            if attr.startswith(_CAPTURE_EXEMPT_PREFIXES):
                continue
            writes.setdefault(fn.cls, {}).setdefault(attr, fn.name)
    for qual in sorted(traced):
        fn = mod.functions.get(qual)
        if fn is None or fn.cls is None:
            continue
        cls_writes = writes.get(fn.cls, {})
        flagged: Set[str] = set()
        for attr in fn.self_reads:
            if attr in cls_writes and attr not in flagged and \
                    attr not in fn.self_writes:
                flagged.add(attr)
                findings.append(Finding(
                    RULE, info.path, fn.line,
                    f"traced `{fn.name}` closes over `self.{attr}`, "
                    f"which `{fn.cls}.{cls_writes[attr]}` mutates "
                    "host-side — the traced read is baked at trace "
                    "time",
                    hint="thread the value through the call as an "
                         "operand (data) or a rebuild-triggering "
                         "config (static), never mutable self state"))


def check(info: ModuleInfo,
          project: Optional[Project] = None) -> List[Finding]:
    if not info.is_hot_path:
        return []
    findings: List[Finding] = []
    mod = project.modules.get(info.path) if project else None
    static_jit = dict(mod.static_jit) if mod else {}
    jit_callables: Set[str] = set(mod.jit_names) if mod else set()
    jit_callables |= {"self." + a for a in (mod.jit_attrs if mod else [])}
    if project is not None:
        jit_callables |= project.imported_jit_names(info.path)
        # an IMPORTED static-arg jit binding carries its spec across
        # the module boundary — the unbounded-compile hazard must not
        # go silent exactly when the call graph was built to see it
        if mod is not None:
            for local, (target, attr) in mod.imports.items():
                if attr is None:
                    continue
                target_mod = project.modules.get(target)
                if target_mod is not None and \
                        attr in target_mod.static_jit and \
                        local not in static_jit:
                    static_jit[local] = target_mod.static_jit[attr]
    # summaries key self-attr statics as "self.<attr>"; scope walks see
    # the same spelling via dotted_name, so the dict lines up
    traced_quals: Set[str] = set()
    if project is not None:
        traced_quals = {q for (m, q) in project.traced_reachable()
                        if m == info.path}
    nodes = function_nodes(info)
    for qual, fn_node in sorted(nodes.items()):
        if qual in traced_quals:
            continue  # calls INSIDE a trace re-trace anyway
        walker = _HazardWalk(info, static_jit, jit_callables, findings)
        for stmt in fn_node.body:
            walker.visit(stmt)
    if project is not None:
        _mutable_capture(info, project, findings)
    return findings
