"""signal-safety — nothing reachable from a signal handler may block.

A CPython signal handler runs BETWEEN bytecodes of whatever the main
thread happened to be doing.  If that was ``Tracer._emit_complete``
holding the tracer lock, a handler that flushes telemetry deadlocks the
process on its own lock (the PR 4 bug: ``flush_metrics`` from the
SIGTERM handler); if it was a buffered ``fh.write``, a handler write
raises a reentrancy error; ``logging`` takes module-level locks and is
documented as unsafe in handlers.  At fleet scale (ROADMAP item 5:
days-long endurance runs under preemption) a one-in-a-million handler
race is a daily hang, so the discipline is machine-checked:

from every handler registered via ``signal.signal(sig, h)`` the project
call graph is closed, and every reachable function is held to the
async-signal-safe subset — flagged facts are lock acquisitions (with
statements on lock-named objects, explicit ``.acquire()``), file IO
(``open``), logging (``print``/``print_rank``/``logging.*``/logger
level methods), blocking operations (zero-arg ``.join()``, ``.wait()``,
``time.sleep``) and explicit ``jax.device_get`` syncs.

The blessed fix is the DEFERRED-FLUSH pattern
(``resilience/preemption.py``): the handler only sets flags; the round
loop's next poll — outside signal context — runs the flush.
Statically, work lexically inside the BODY of an ``if not <flag>:``
whose negated test names a ``*_from_signal``-style flag is treated as
deferred and pruned from the handler closure, so the idiom's carrier
function stays clean while an UNguarded flush three calls deep still
flags with its handler path.  Polarity is checked: ``if _from_signal:``
bodies (and else-branches) run IN signal context and keep flagging.

``os.write`` to a raw fd is async-signal-safe and deliberately not in
the flagged set — it is the sanctioned way to say something from a
handler that must speak even when the process is wedged.
"""

from __future__ import annotations

from typing import List, Optional, Set, Tuple

from .core import Finding, FunctionSummary, Project

RULE = "signal-safety"

#: conc-op kinds unsafe in signal context, with human phrasing
_UNSAFE_OPS = {
    "lock-acquire": "acquires lock `{d}`",
    "file-io": "opens a file",
    "log": "logs via `{d}` (logging takes module-level locks)",
    "blocking-join": "joins `{d}` (blocks the interrupted thread)",
    "blocking-wait": "waits on `{d}`",
    "blocking-sleep": "sleeps",
}

_HINT = ("signal handlers may only set flags (threading.Event, plain "
         "attributes) and os.write to raw fds; defer the real work to a "
         "flag polled by the loop — the preemption deferred-flush "
         "pattern (resilience/preemption.py), whose `if not "
         "_from_signal:` guard this rule recognizes")


def _in_deferred(fn: FunctionSummary, line: int) -> bool:
    return any(s <= line <= e for s, e in fn.deferred_spans)


def check_project(project: Project,
                  emit_paths: Optional[Set[str]] = None
                  ) -> List[Finding]:
    roots: List[Tuple[str, str]] = []
    for path, mod in project.modules.items():
        for ref, _line, cls in mod.signal_handlers:
            resolved = project.resolve(path, ref, cls)
            if resolved:
                roots.append(resolved)
    if not roots:
        return []
    # the shared closure walk, minus call edges inside deferred
    # (signal-flag-guarded) spans
    parents = project.reachable_from(sorted(set(roots)),
                                     skip_edge=_in_deferred)

    findings: List[Finding] = []
    for key in sorted(parents):
        fn = project.function(key)
        if fn is None:
            continue
        if emit_paths is not None and fn.module not in emit_paths:
            continue
        chain = project.call_path(parents, key)
        via = (f" (handler path: {' -> '.join(chain)})"
               if len(chain) > 1 else " (registered signal handler)")
        for kind, line, detail in fn.conc_ops:
            phrase = _UNSAFE_OPS.get(kind)
            if phrase is None or _in_deferred(fn, line):
                continue
            findings.append(Finding(
                RULE, fn.module, line,
                f"`{fn.qual}` {phrase.format(d=detail or '?')} but is "
                f"reachable from a signal handler{via}", hint=_HINT))
        for lock, start, _end in fn.lock_regions:
            if _in_deferred(fn, start):
                continue
            findings.append(Finding(
                RULE, fn.module, start,
                f"`{fn.qual}` acquires lock `{lock}` but is reachable "
                f"from a signal handler — if the interrupted thread "
                f"holds it, the process deadlocks on itself{via}",
                hint=_HINT))
        for line, arg, _loop in fn.device_gets:
            if _in_deferred(fn, line):
                continue
            findings.append(Finding(
                RULE, fn.module, line,
                f"`{fn.qual}` device_get of `{arg}` but is reachable "
                f"from a signal handler — a device sync mid-handler can "
                f"block indefinitely{via}", hint=_HINT))
    return findings
