"""thread-escape — mutable state handed across a thread boundary.

The torn-snapshot class (PR 1's first satellite fix): the training
thread submits a pytree to the async checkpoint writer's mailbox, then
keeps mutating its numpy leaves in place — the writer's serialize reads
a value that is half round k, half round k+1, and the checkpoint
passes its own checksum because the tear happened BEFORE the write.
Nothing crashes; the corruption surfaces rounds later on resume.

Facts: thread roots are discovered from ``threading.Thread(target=…)``
spawns and closed over the project call graph — the writer thread, any
thread a future fleet-mode PR adds.  A ``self.X`` attribute assigned on
the MAIN side (any function outside the worker closure) and read inside
the worker closure of the same class is a cross-thread channel; the
assigned value must be a snapshot:

- a copy (``np.copy``/``jnp.copy``/``copy.deepcopy``/``.copy()`` —
  matched anywhere in the value source, so a ``jax.tree.map`` whose
  lambda copies its leaves passes; provenance follows bare local names
  a few assignments deep);
- a freshly constructed object (``dict(…)``/``list(…)``/capitalized
  constructor calls) or an immutable literal/constant.

A bare name or a plain call result (``self._mailbox = _payload(state)``
— the exact pre-fix bug) flags.  Writes in ``__init__`` are exempt: the
constructor runs before the class can have spawned its thread.
Subscript stores (``self._cache[k] = v``) are lock-discipline's
territory, not a handoff.

Spawn hygiene rides along: an ANONYMOUS ``Thread(…)`` spawn (no
``name=``) in a hot-path module flags — telemetry puts every span on a
named thread track and watchdog/event records carry the emitting thread
name, so a thread named "Thread-7" is unattributable in every trace and
log the fleet-mode endurance harness will be debugged from.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FunctionSummary, Project, conc_hot_path

RULE = "thread-escape"

#: value-source text that denotes a snapshot/copy
_COPY_RE = re.compile(
    r"copy\(|deepcopy|\.copy\b|asarray\(|"
    r"np\.array\(|dict\(|list\(|tuple\(|frozenset\(")
#: a call whose final callable segment is Capitalized constructs a
#: fresh object — no aliasing with training-thread state
_CTOR_RE = re.compile(r"^[A-Za-z_][\w.]*\.?[A-Z]\w*\(")
#: immutable SCALAR values need no copy.  Container displays are NOT
#: here on purpose: `self._box = (state, 1)` builds a fresh tuple
#: around the LIVE `state` object — the tear happens through the
#: element, so a display only passes when its contents copy (matched
#: by _COPY_RE) or it holds nothing but literals (checked below).
_LITERAL_RE = re.compile(r"^(None|True|False|[-+]?\d|[\"'])")
#: a container display with no bare-name element references: every
#: identifier inside is a callable/attribute head (`np.copy(`,
#: `dict(`), never a naked aliasing reference
_PURE_DISPLAY_RE = re.compile(r"^[(\[{][^A-Za-z_]*[)\]}]$")


def _copy_like(src: str, local_assigns: Dict[str, str],
               depth: int = 3) -> bool:
    src = src.strip()
    if not src:
        return False
    # string literals are immutable — blank them out before the
    # pure-display test so `("tag", 1)` reads as identifier-free
    quoteless = re.sub(r"'[^']*'|\"[^\"]*\"", "''", src)
    if _COPY_RE.search(src) or _CTOR_RE.match(src) or \
            _LITERAL_RE.match(src) or _PURE_DISPLAY_RE.match(quoteless):
        return True
    if depth > 0 and re.fullmatch(r"[A-Za-z_]\w*", src):
        provenance = local_assigns.get(src)
        if provenance is not None:
            return _copy_like(provenance, local_assigns, depth - 1)
    return False


def check_project(project: Project,
                  emit_paths: Optional[Set[str]] = None
                  ) -> List[Finding]:
    findings: List[Finding] = []
    roots: List[Tuple[str, str]] = []
    for path in sorted(project.modules):
        mod = project.modules[path]
        for target, line, named, cls, _fn in mod.thread_spawns:
            if not named and conc_hot_path(path) and \
                    (emit_paths is None or path in emit_paths):
                findings.append(Finding(
                    RULE, path, line,
                    "anonymous thread spawn — an unnamed thread is "
                    "unattributable in telemetry thread tracks, event "
                    "records and watchdog messages",
                    hint="pass name=... (e.g. threading.Thread(target="
                         "..., name=\"ckpt-latest-writer\")); the name "
                         "rides every span/event the thread emits"))
            if target:
                resolved = project.resolve(path, target, cls)
                if resolved:
                    roots.append(resolved)
    if not roots:
        return findings

    worker = project.reachable_from(sorted(set(roots)))
    #: (defining module, class, attr) -> a worker-side reader to name
    #: in the report.  Module-qualified: a same-named but unrelated
    #: class elsewhere must not inherit this one's channels.
    worker_reads: Dict[Tuple[str, str, str], FunctionSummary] = {}
    for key in worker:
        fn = project.function(key)
        if fn is None or fn.cls is None:
            continue
        for attr in fn.self_reads:
            worker_reads.setdefault((fn.module, fn.cls, attr), fn)

    for path in sorted(project.modules):
        mod = project.modules[path]
        for qual in sorted(mod.functions):
            fn = mod.functions[qual]
            if fn.cls is None or fn.name == "__init__" or \
                    (path, qual) in worker:
                continue
            if emit_paths is not None and path not in emit_paths:
                continue
            for attr, line, src in fn.self_assigns:
                reader = worker_reads.get((path, fn.cls, attr))
                if reader is None:
                    continue
                if _copy_like(src, fn.local_assigns):
                    continue
                findings.append(Finding(
                    RULE, path, line,
                    f"`self.{attr} = {src}` hands live state across a "
                    f"thread boundary — `{reader.module}::{reader.qual}`"
                    " reads it on a spawned thread; an in-place "
                    "mutation on this thread reaches the worker "
                    "mid-operation (the torn-snapshot class)",
                    hint="snapshot before the handoff: np.copy/jnp.copy "
                         "the leaves (jax.tree.map over the pytree, as "
                         "checkpoint._mp_submit does) or hand over an "
                         "immutable/freshly-built value"))
    return findings
