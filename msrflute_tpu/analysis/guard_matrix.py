"""guard-matrix — the refusal matrix, cross-checked layer by layer.

The features that only work on the fused round path (``robust``
screening, ``chaos`` client faults, ``cohort_bucketing``) are guarded
by THREE layers that historically desync:

1. **runtime refusals** — ``raise ValueError`` guards in
   ``engine/server.py`` / ``engine/round.py`` / ``strategies/*.py``
   keyed off the ``host_orchestrated`` predicate and per-feature
   incompatibility checks;
2. **schema bespoke checks** — config-load-time errors in ``schema.py``
   for the incompatibilities already decidable from the raw config
   (robust x strategy, fedbuff x strategy);
3. **documentation** — the per-feature compatibility tables in
   ``docs/config_extensions.md`` ("Refused with ...", "Incompatible
   with ...").

A new strategy or config block can dodge ONE layer silently; it cannot
dodge this rule:

- every strategy-class host marker (a class-level ``*_rounds = True``
  in ``strategies/``) must be consulted by the ``host_orchestrated``
  predicate in ``engine/server.py``;
- every guarded block must have a runtime refusal naming it;
- every incompatibility a runtime refusal names (tokens from
  :data:`VOCAB`) must appear in that block's
  ``docs/config_extensions.md`` section — the operator-facing table
  can't silently lag the code;
- every incompatibility the DOCS promise ("Refused/Incompatible with
  `X`") must appear in some runtime refusal or schema check for that
  block — the code can't silently drop a documented guard;
- every COMPOSITION the docs promise ("Composes with `X` ...
  (`tests/test_y.py`)") must cite a test file, and the cited file must
  actually exercise each composed :data:`VOCAB` token — a compatibility
  claim nobody tests is the refusal matrix's mirror-image failure
  (the pair runs, silently wrong, instead of refusing);
- a documented composition must not be CONTRADICTED by a live runtime
  refusal: if the block's docs claim it composes with token `X` while
  one of the block's refusal messages still says `X` "does not compose
  with"/"is incompatible with" it, one of the two layers is stale —
  exactly what happens when a refusal is lifted in docs but a guard
  site is missed (or re-introduced by a revert);
- blocks in :data:`SCHEMA_GUARDED` must keep their config-load-time
  strategy check in ``schema.py``.

All literal extraction (raise-message string constants, doc sections);
no imports of the checked modules.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Tuple

from .core import Finding

RULE = "guard-matrix"

#: config blocks that require the fused round path at runtime
GUARDED_BLOCKS = ("robust", "chaos", "cohort_bucketing", "megabatch",
                  # fluteflow arrival plane (PR 19): the refusal ladder
                  # covers host-orchestrated rounds, the buffer==cohort
                  # geometry, fleet sampling modes, the secure_agg
                  # liveness floor, and megabatch x traced staleness
                  "traffic")

#: the incompatibility vocabulary the matrix is checked over: config
#: keys, strategy names and flags that appear in refusal messages and
#: compatibility tables.  A token outside this list is prose, not a
#: matrix cell.
VOCAB = ("wantRL", "scaffold", "ef_quant", "personalization",
         "clients_per_chunk", "adaptive_clipping", "dump_norm_stats",
         "secure_agg", "input_staging", "fused_carry", "stale_prob",
         "fedavg", "fedprox",
         # cross-client megabatching refusal tokens (PR 16)
         "apply_metrics", "fedlabels", "pallas_apply",
         # fleet/mesh-era composition tokens (PR 17): strategies that
         # pre-bucket their cohort and the paged-carry interplay
         "wants_cohort",
         # fluteflow arrival-plane token (PR 19): the traffic block
         # itself, so other blocks' traffic refusals are matrix cells
         "traffic")

#: blocks whose strategy incompatibility is decidable at config load —
#: schema.py must carry the bespoke check (the quiet-failure rule)
SCHEMA_GUARDED = ("robust", "fedbuff", "megabatch")

#: class-attr suffix marking a strategy as host-orchestrated; every
#: marker any strategy sets must appear in the predicate
MARKER_SUFFIX = "_rounds"

_DOC_REFUSAL_RE = re.compile(
    r"(refused with|incompatible with|rejected under)", re.I)

#: composition-claim sentence start / end-of-claim boundaries (the
#: refusal sentence usually follows in the SAME paragraph)
_COMPOSE_RE = re.compile(r"composes with", re.I)
_COMPOSE_END_RE = re.compile(
    r"Refused with|Requires |Incompatible with|Rejected under")
_TEST_CITE_RE = re.compile(r"`(tests/[\w\-/]+\.py)`")

#: refusal phrasings that flatly deny a composition — a raise carrying
#: one of these next to a token the docs CLAIM to compose with marks a
#: stale guard site (refusal lifted in docs, missed in code).  Refusals
#: that merely constrain HOW a pair composes ("use aggregator: mean")
#: must avoid this phrasing — that's the convention this layer enforces.
_CONTRADICT_RE = re.compile(
    r"(does not compose with|incompatible with)", re.I)


def _parse(path: str, trees: Optional[Dict[str, ast.Module]],
           root: str) -> Optional[ast.Module]:
    if trees is not None:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        tree = trees.get(rel)
        if tree is not None:
            return tree
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return None


def _raise_texts(tree: Optional[ast.Module]) -> List[Tuple[int, str]]:
    """(line, concatenated-constant-text) for every ``raise X(msg)``."""
    if tree is None:
        return []
    out: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Raise) and
                isinstance(node.exc, ast.Call) and node.exc.args):
            continue
        parts: List[str] = []
        for sub in ast.walk(node.exc.args[0]):
            if isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str):
                parts.append(sub.value)
        if parts:
            out.append((node.lineno, " ".join(parts)))
    return out


def _string_constants(tree: Optional[ast.Module]) -> List[str]:
    if tree is None:
        return []
    return [node.value for node in ast.walk(tree)
            if isinstance(node, ast.Constant) and
            isinstance(node.value, str)]


def _doc_section(doc_lines: List[str], block: str
                 ) -> Optional[Tuple[int, List[str]]]:
    """The config_extensions section for ``block``: from the heading
    mentioning ``server_config.<block>`` (or the block's table row) to
    the next heading of the same or higher level."""
    needle = f"server_config.{block}"
    start = level = None
    for i, line in enumerate(doc_lines):
        if line.lstrip().startswith("#") and needle in line:
            start = i
            level = len(line) - len(line.lstrip("#"))
            break
    if start is None:
        return None
    end = len(doc_lines)
    for i in range(start + 1, len(doc_lines)):
        line = doc_lines[i]
        if line.startswith("#") and \
                len(line) - len(line.lstrip("#")) <= (level or 1):
            end = i
            break
    return (start + 1, doc_lines[start:end])


def _tokens_in(text: str) -> List[str]:
    low = text.lower()
    return [t for t in VOCAB if t.lower() in low]


def check_project(root: str,
                  trees: Optional[Dict[str, ast.Module]] = None
                  ) -> List[Finding]:
    """``trees`` optionally carries already-parsed module ASTs keyed by
    rel path (the analyze() fast path); files absent from it are parsed
    from disk."""
    pkg = os.path.join(root, "msrflute_tpu")
    server_path = os.path.join(pkg, "engine", "server.py")
    schema_path = os.path.join(pkg, "schema.py")
    doc_path = os.path.join(root, "docs", "config_extensions.md")
    if not (os.path.exists(server_path) and os.path.exists(schema_path)):
        return []  # not a tree this checker applies to

    rel_server = os.path.relpath(server_path, root).replace(os.sep, "/")
    rel_schema = os.path.relpath(schema_path, root).replace(os.sep, "/")
    findings: List[Finding] = []

    with open(server_path, "r", encoding="utf-8") as fh:
        server_src = fh.read()

    # ---- 1. strategy host markers all reach the predicate ------------
    strategy_files = sorted(
        glob.glob(os.path.join(pkg, "strategies", "*.py")))
    markers: Dict[str, str] = {}  # marker attr -> defining file::class
    for spath in strategy_files:
        tree = _parse(spath, trees, root)
        if tree is None:
            continue
        rel = os.path.relpath(spath, root).replace(os.sep, "/")
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name) and \
                        stmt.targets[0].id.endswith(MARKER_SUFFIX) and \
                        isinstance(stmt.value, ast.Constant) and \
                        stmt.value.value is True:
                    markers.setdefault(stmt.targets[0].id,
                                       f"{rel}::{node.name}")
    for marker, where in sorted(markers.items()):
        if marker not in server_src:
            findings.append(Finding(
                RULE, rel_server, 1,
                f"strategy host marker `{marker}` (set by {where}) is "
                "not consulted by engine/server.py — its strategy "
                "dodges the host_orchestrated refusal matrix",
                hint="add `getattr(self.strategy, '" + marker + "', "
                     "False)` to the host_orchestrated predicate (and "
                     "to _pipeline_capable if it forces serial)"))

    # ---- gather runtime refusal texts per guarded block --------------
    guard_files = sorted(
        glob.glob(os.path.join(pkg, "engine", "*.py")) +
        glob.glob(os.path.join(pkg, "strategies", "*.py")) +
        glob.glob(os.path.join(pkg, "robust", "*.py")))
    block_raises: Dict[str, List[Tuple[str, int, str]]] = \
        {b: [] for b in GUARDED_BLOCKS}
    for gpath in guard_files:
        rel = os.path.relpath(gpath, root).replace(os.sep, "/")
        for line, text in _raise_texts(_parse(gpath, trees, root)):
            for block in GUARDED_BLOCKS:
                if block in text:
                    block_raises[block].append((rel, line, text))

    doc_lines: List[str] = []
    if os.path.exists(doc_path):
        with open(doc_path, "r", encoding="utf-8") as fh:
            doc_lines = fh.read().splitlines()
    rel_doc = os.path.relpath(doc_path, root).replace(os.sep, "/") \
        if doc_lines else None

    schema_tree = _parse(schema_path, trees, root)
    schema_strings = _string_constants(schema_tree)
    # the matrix only covers blocks this tree's schema actually knows —
    # a fork that dropped cohort_bucketing owes no guard for it
    server_keys: set = set()
    if schema_tree is not None:
        for node in schema_tree.body:
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == "SERVER_KEYS" and \
                    isinstance(node.value, ast.Set):
                server_keys = {e.value for e in node.value.elts
                               if isinstance(e, ast.Constant)}

    for block in GUARDED_BLOCKS:
        if server_keys and block not in server_keys:
            continue
        raises = block_raises[block]
        # ---- 2. runtime layer exists ---------------------------------
        if not raises:
            findings.append(Finding(
                RULE, rel_server, 1,
                f"guarded block `{block}` has no runtime refusal in "
                "engine/ or strategies/ — a host-orchestrated config "
                "would silently run it degraded",
                hint="raise at server construction when "
                     f"server_config.{block} meets an incompatible "
                     "path, like the robust/chaos guards"))
            continue
        if not doc_lines:
            continue
        section = _doc_section(doc_lines, block)
        if section is None:
            findings.append(Finding(
                RULE, rel_doc or rel_server, 1,
                f"guarded block `{block}` has runtime refusals but no "
                "docs/config_extensions.md section",
                hint="add the per-key table + compatibility notes the "
                     "other blocks carry"))
            continue
        sec_line, sec_lines = section
        sec_text = "\n".join(sec_lines)
        # ---- 3. code -> docs: every refusal token is documented ------
        code_tokens = sorted({t for _, _, text in raises
                              for t in _tokens_in(text)})
        for token in code_tokens:
            if token.lower() not in sec_text.lower():
                src = ", ".join(sorted({f"{rel}:{line}"
                                        for rel, line, text in raises
                                        if token in _tokens_in(text)}))
                findings.append(Finding(
                    RULE, rel_doc, sec_line,
                    f"`server_config.{block}` refuses `{token}` at "
                    f"runtime ({src}) but its config_extensions "
                    "section never mentions it",
                    hint="add the incompatibility to the section's "
                         "'Refused with'/'Incompatible with' list"))
        # ---- 4. docs -> code: every documented refusal is enforced ---
        doc_tokens: List[Tuple[int, str]] = []
        for i, line in enumerate(sec_lines):
            if not _DOC_REFUSAL_RE.search(line):
                continue
            # the refusal sentence may wrap: scan to the next blank line
            chunk: List[str] = []
            for j in range(i, len(sec_lines)):
                if not sec_lines[j].strip():
                    break
                chunk.append(sec_lines[j])
            joined = " ".join(chunk)
            # a composition sentence sharing the paragraph is NOT part
            # of the refusal list (layer 5 owns its tokens)
            comp = _COMPOSE_RE.search(joined)
            if comp is not None:
                joined = joined[:comp.start()]
            for token in _tokens_in(joined):
                doc_tokens.append((sec_line + i, token))
        enforced = " ".join(text for _, _, text in raises) + " " + \
            " ".join(s for s in schema_strings if block in s)
        enforced_tokens = set(_tokens_in(enforced))
        for line_no, token in sorted(set(doc_tokens)):
            if token not in enforced_tokens:
                findings.append(Finding(
                    RULE, rel_doc, line_no,
                    f"docs promise `server_config.{block}` is refused "
                    f"with `{token}`, but no runtime guard or schema "
                    "check enforces it",
                    hint="re-add the refusal or fix the doc — an "
                         "unenforced compatibility table is how silent "
                         "corruption ships"))

        # ---- 5. composition claims are exercised by the cited test ---
        # "Composes with A, B (`tests/test_x.py`)" is a promise with the
        # same weight as a refusal: each VOCAB token in the claim must
        # appear in the cited test file (the composition-case suite),
        # and the claim must cite one at all.
        blob = " ".join(sec_lines)
        claimed_tokens: set = set()
        for m in _COMPOSE_RE.finditer(blob):
            end = _COMPOSE_END_RE.search(blob, m.end())
            chunk = blob[m.start():end.start() if end else len(blob)]
            comp_tokens = _tokens_in(chunk)
            claimed_tokens.update(comp_tokens)
            claim_line = sec_line
            for i, line in enumerate(sec_lines):
                if _COMPOSE_RE.search(line):
                    claim_line = sec_line + i
                    break
            cite = _TEST_CITE_RE.search(chunk)
            if cite is None:
                if comp_tokens:
                    findings.append(Finding(
                        RULE, rel_doc, claim_line,
                        f"`server_config.{block}` claims to compose "
                        f"with {', '.join(f'`{t}`' for t in comp_tokens)}"
                        " but cites no test file for the claim",
                        hint="append the composition suite citation "
                             "(`tests/test_<block>.py`) the other "
                             "blocks carry — an uncited composition "
                             "claim is unfalsifiable"))
                continue
            cite_path = os.path.join(root, cite.group(1))
            if not os.path.exists(cite_path):
                findings.append(Finding(
                    RULE, rel_doc, claim_line,
                    f"`server_config.{block}`'s composition claim "
                    f"cites `{cite.group(1)}`, which does not exist",
                    hint="fix the citation or add the suite"))
                continue
            with open(cite_path, "r", encoding="utf-8") as fh:
                cite_src = fh.read()
            for token in comp_tokens:
                if token not in cite_src:
                    findings.append(Finding(
                        RULE, rel_doc, claim_line,
                        f"docs promise `server_config.{block}` composes "
                        f"with `{token}`, but the cited "
                        f"`{cite.group(1)}` never exercises that "
                        "config-key combination",
                        hint="add the composition case (the suite's "
                             "COMPOSE_CASES pattern: run the pair, "
                             "assert bitwise parity with the unfused "
                             "path) or drop the claim — an untested "
                             "composition promise ships the silent "
                             "version of a missing refusal"))

        # ---- 5b. claims vs refusals: no contradiction ----------------
        # a composition the docs promise for this block must not still
        # be flatly refused by one of the block's own guard sites — the
        # config would raise on exactly the pair the docs advertise.
        for token in sorted(claimed_tokens):
            for rel, line, text in raises:
                if token in _tokens_in(text) and \
                        _CONTRADICT_RE.search(text):
                    findings.append(Finding(
                        RULE, rel, line,
                        f"docs claim `server_config.{block}` composes "
                        f"with `{token}`, but this refusal still says "
                        "it does not — a stale guard site (or a stale "
                        "claim)",
                        hint="lift the refusal (and cover the pair in "
                             "the cited composition suite) or retract "
                             "the docs claim; a refusal that only "
                             "constrains HOW the pair composes should "
                             "avoid 'does not compose with'/"
                             "'incompatible with' phrasing"))

    # ---- 6. schema bespoke layer -------------------------------------
    for block in SCHEMA_GUARDED:
        if server_keys and block not in server_keys:
            continue  # a fork that dropped the block owes no guard
        held = any(block in s and "strategy" in s
                   for s in schema_strings)
        if not held:
            findings.append(Finding(
                RULE, rel_schema, 1,
                f"`server_config.{block}` has no config-load-time "
                "strategy check in schema.py — the refusal only fires "
                "at server construction",
                hint="add the bespoke validate() error (the "
                     "secure_agg/fedbuff quiet-failure rule): the "
                     "strategy incompatibility is decidable from the "
                     "raw config"))
    return findings
