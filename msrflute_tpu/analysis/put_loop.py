"""put-loop — per-leaf ``jax.device_put`` loops in hot-path modules.

The dispatch half of the flatpack discipline (PR 6): a faithful round's
host inputs cross the host->device boundary as ONE staged buffer per
dtype group (``utils/flatpack.py`` ``AxisPacker``/``ScalarStager``, one
``device_put`` per group).  A ``device_put`` inside a loop or
comprehension pays one transfer per iteration instead — exactly the
~8-10 per-leaf puts per dispatch that ``tools/dispatch_cost_probe.py``
measured (~88 ms suspect on a remote-attached chip) and that
``server_config.input_staging`` removed.

Flagged, in hot-path modules only (``engine/``, ``ops/``,
``strategies/``, ``telemetry/``, ``robust/``): any
``jax.device_put(...)`` / ``device_put(...)`` call lexically inside a
``for``/``while`` body or a list/set/dict comprehension / generator
expression.

Deliberately lexical (no data-flow): a put whose operand is a packed
per-dtype dict is ONE call on the whole tree and never sits in a loop;
the loop shape IS the smell.  Function/lambda bodies reset the loop
context — a staging closure defined inside a loop is called elsewhere
and judged there.  Legitimate loops (one-time pool uploads, legacy
A/B paths kept for ``tools/dispatch_cost_probe.py``) carry a
``# flint: disable=put-loop reason`` pragma.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, ModuleInfo, call_name

RULE = "put-loop"

_PUT_NAMES = {"jax.device_put", "device_put"}
_LOOPS = (ast.For, ast.AsyncFor, ast.While, ast.ListComp, ast.SetComp,
          ast.DictComp, ast.GeneratorExp)
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def check(info: ModuleInfo) -> List[Finding]:
    if not info.is_hot_path:
        return []
    findings: List[Finding] = []

    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPES):
                # new call boundary: the body runs when the function is
                # called, not per iteration of any enclosing loop
                walk(child, False)
                continue
            child_in_loop = in_loop or isinstance(child, _LOOPS)
            if isinstance(child, ast.Call) and child_in_loop and \
                    call_name(child) in _PUT_NAMES:
                findings.append(Finding(
                    RULE, info.path, child.lineno,
                    "device_put inside a loop/comprehension pays one "
                    "host->device transfer per iteration",
                    hint="pack the leaves into one staged buffer per "
                         "dtype group (utils/flatpack.py AxisPacker/"
                         "ScalarStager) and device_put once, or put the "
                         "whole tree in a single call"))
            walk(child, child_in_loop)

    walk(info.tree, False)
    return findings
