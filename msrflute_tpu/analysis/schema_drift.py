"""schema-drift — schema.py vs config.py vs docs, cross-checked.

The config surface lives in three places that historically desync:
``schema.py`` (the validation vocabulary), ``config.py`` (the dataclass
defaults), and the operator docs.  A key present in one but not the
others is a silent failure: the dataclass accepts it while validation
rejects it (or validation accepts a knob nothing reads), and an
operator copies a documented knob the schema meanwhile dropped.

Checks (all literal-extraction — no imports of the checked modules):

1. every dataclass field of ``ServerConfig`` / ``ClientConfig`` /
   ``DatasetConfig`` (minus the ``extra`` catch-all and private names)
   appears in the matching ``*_KEYS`` set in schema.py;
2. every key in ``SERVER/CLIENT/DATASET_FIELD_SPECS`` appears in the
   matching ``*_KEYS`` set (a type rule for an unknown key is dead);
3. every ``server_config.X`` / ``client_config.X`` dotted mention in
   ``docs/*.md`` + ``README.md`` names a key the schema knows;
4. the TPU-native operator knobs in :data:`DOCUMENTED_KNOBS` are
   mentioned in ``docs/RUNBOOK.md`` — the knobs whose absence from the
   runbook has already cost chip time (``pipeline_depth`` class).
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Set

from .core import Finding

RULE = "schema-drift"

#: schema key-set name -> config.py dataclass it must cover
_SECTION_MAP = {
    "SERVER_KEYS": "ServerConfig",
    "CLIENT_KEYS": "ClientConfig",
    "DATASET_KEYS": "DatasetConfig",
}
_SPEC_MAP = {
    "SERVER_FIELD_SPECS": "SERVER_KEYS",
    "CLIENT_FIELD_SPECS": "CLIENT_KEYS",
    "DATASET_FIELD_SPECS": "DATASET_KEYS",
    # resilience blocks (PR 3): their type rules must describe keys the
    # unknown-key pass knows, like every other section
    "CHAOS_FIELD_SPECS": "CHAOS_KEYS",
    "CHECKPOINT_RETRY_FIELD_SPECS": "CHECKPOINT_RETRY_KEYS",
    # flutearmor's infrastructure fault plane (PR 20): the nested
    # chaos.infra mapping has its own key set + spec table
    "CHAOS_INFRA_FIELD_SPECS": "CHAOS_INFRA_KEYS",
    # flutescope telemetry blocks (PR 4)
    "TELEMETRY_FIELD_SPECS": "TELEMETRY_KEYS",
    "WATCHDOG_FIELD_SPECS": "WATCHDOG_KEYS",
    # fluteshield screened aggregation (PR 5)
    "ROBUST_FIELD_SPECS": "ROBUST_KEYS",
    # cohort shape-bucketing (PR 8)
    "COHORT_BUCKETING_FIELD_SPECS": "COHORT_BUCKETING_KEYS",
    # megakernel local SGD (PR 12); the precision block's fields are
    # enum-typed (dtype names) so it keeps bespoke checks in validate()
    # and has no scalar spec table
    "MEGAKERNEL_FIELD_SPECS": "MEGAKERNEL_KEYS",
    # fleet mode (PR 14); `sampling` is enum-typed and keeps its
    # bespoke check in validate()
    "FLEET_FIELD_SPECS": "FLEET_KEYS",
    # cross-client megabatching (PR 16); the cohort_bucketing
    # prerequisite is a cross-block rule and stays bespoke in validate()
    "MEGABATCH_FIELD_SPECS": "MEGABATCH_KEYS",
    # straggler-tolerant secure aggregation (PR 18); `graph` is
    # enum-typed and keeps its bespoke check in validate()
    "SECURE_AGG_FIELD_SPECS": "SECURE_AGG_KEYS",
    # fluteflow arrival plane (PR 19); `mode`/`trace` are enum-typed
    # and `classes` is a list-of-mappings — those keep bespoke checks
    # in validate()
    "TRAFFIC_FIELD_SPECS": "TRAFFIC_KEYS",
}
#: structural keys docs may mention with further dotted children
_STRUCTURAL = {"data_config", "optimizer_config", "annealing_config",
               "server_replay_config", "RL", "secure_agg", "fedbuff",
               "nbest_task_scheduler"}

#: TPU-native knobs the RUNBOOK must document (each one already has an
#: operator-facing behavior difference; an undocumented one is how
#: `pipeline_depth`-class knobs silently desync from practice)
DOCUMENTED_KNOBS = (
    "pipeline_depth", "rounds_per_step", "checkpoint_async",
    "checkpoint_backend", "compilation_cache_dir", "step_bucketing",
    # universal overlap (PR 6): an operator who cannot find the carry /
    # staging knobs will keep paying the serial fallback and the
    # per-leaf dispatch tax without knowing the lever exists
    "fused_carry", "input_staging",
    # resilience knobs: an operator who cannot find the preemption /
    # fault-injection drill in the runbook will learn about it from a
    # lost run instead
    "chaos", "checkpoint_retry",
    # flutescope: an operator who cannot find the trace/watchdog knobs
    # will keep debugging round time from log lines
    "telemetry",
    # fluteshield: an operator who cannot find the screened-aggregation
    # drill will learn about poisoned cohorts from a diverged model
    "robust",
    # cohort shape-bucketing: an operator who cannot find the bucket
    # tuning drill will keep paying masked FLOPs padding every client
    # to the slowest one
    "cohort_bucketing",
    # megakernel local SGD: an operator who cannot find the fusion /
    # pallas-apply knobs will keep paying per-epoch program bloat and
    # sub-MXU optimizer tails on small models
    "megakernel",
    # precision policy: an operator who cannot find the bf16 drill will
    # leave the MXU's half-rate f32 path on forever — or flip dtypes
    # blind and lose bit-identity without knowing what they traded
    "precision",
    # fleet mode: an operator who cannot find the paging / O(cohort)
    # sampling drill will keep sizing HBM by population and believe
    # million-client runs are impossible
    "fleet",
    # cross-client megabatching: an operator who cannot find the lane
    # tuning drill will keep paying the padded [K, S] grid on every
    # heterogeneous cohort a coarse bucket layout produces
    "megabatch",
    # fluteflow arrival plane: an operator who cannot find the traffic
    # drill will keep benchmarking async strategies against a
    # boundary-sampled timeline where their whole reason to exist —
    # rounds-to-target under real arrivals — is unmeasurable
    "traffic",
    # flutearmor infra fault plane: an operator who cannot find the
    # infrastructure-fault drill will rehearse cohort failures but meet
    # host-service failures (dead prefetch daemon, flaky row store) for
    # the first time mid-campaign
    "infra",
)

_DOC_MENTION_RE = re.compile(
    r"\b(server_config|client_config)\.([A-Za-z_][A-Za-z0-9_]*)")


def _literal_names(node: ast.AST) -> Optional[Set[str]]:
    """String elements of a set/dict literal (dict -> its keys)."""
    if isinstance(node, ast.Set):
        out = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
        return out
    if isinstance(node, ast.Dict):
        out = set()
        for key in node.keys:
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                out.add(key.value)
        return out
    return None


def _module_literal_sets(path: str) -> Dict[str, Set[str]]:
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    out: Dict[str, Set[str]] = {}
    lines: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            names = _literal_names(node.value)
            if names is not None:
                out[node.targets[0].id] = names
                lines[node.targets[0].id] = node.lineno
    out["__lines__"] = lines  # type: ignore[assignment]
    return out


def _dataclass_fields(path: str) -> Dict[str, Set[str]]:
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            fields = set()
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
            out[node.name] = fields
    return out


def check_project(root: str,
                  schema_path: Optional[str] = None,
                  config_path: Optional[str] = None,
                  doc_paths: Optional[List[str]] = None,
                  runbook_path: Optional[str] = None,
                  documented_knobs=DOCUMENTED_KNOBS) -> List[Finding]:
    schema_path = schema_path or os.path.join(root, "msrflute_tpu",
                                              "schema.py")
    config_path = config_path or os.path.join(root, "msrflute_tpu",
                                              "config.py")
    if not (os.path.exists(schema_path) and os.path.exists(config_path)):
        return []  # not a tree this checker applies to
    if doc_paths is None:
        doc_paths = sorted(glob.glob(os.path.join(root, "docs", "*.md")))
        readme = os.path.join(root, "README.md")
        if os.path.exists(readme):
            doc_paths.append(readme)
    if runbook_path is None:
        runbook_path = os.path.join(root, "docs", "RUNBOOK.md")

    findings: List[Finding] = []
    rel_schema = os.path.relpath(schema_path, root).replace(os.sep, "/")
    rel_config = os.path.relpath(config_path, root).replace(os.sep, "/")

    sets = _module_literal_sets(schema_path)
    set_lines: Dict[str, int] = sets.pop("__lines__", {})  # type: ignore
    classes = _dataclass_fields(config_path)

    # 1. dataclass fields covered by the schema vocabulary
    for keys_name, cls_name in _SECTION_MAP.items():
        keys = sets.get(keys_name)
        fields = classes.get(cls_name)
        if keys is None or fields is None:
            continue
        for fname in sorted(fields):
            if fname == "extra" or fname.startswith("_"):
                continue
            if fname not in keys:
                findings.append(Finding(
                    RULE, rel_config, 1,
                    f"{cls_name}.{fname} is a dataclass field but missing "
                    f"from schema.{keys_name}",
                    hint=f"add {fname!r} to {keys_name} (or drop the "
                         "field) — validation currently rejects a key "
                         "the config tree accepts"))

    # 2. field specs must describe known keys
    for specs_name, keys_name in _SPEC_MAP.items():
        specs = sets.get(specs_name)
        keys = sets.get(keys_name)
        if specs is None or keys is None:
            continue
        for key in sorted(specs - keys):
            findings.append(Finding(
                RULE, rel_schema, set_lines.get(specs_name, 1),
                f"{specs_name}[{key!r}] has a type rule but {key!r} is "
                f"not in {keys_name}",
                hint=f"add {key!r} to {keys_name} or delete the dead "
                     "spec — as is, the key errors as unknown before "
                     "its type is ever checked"))

    # 3. doc mentions must name schema-known keys
    doc_keys = {"server_config": sets.get("SERVER_KEYS", set()),
                "client_config": sets.get("CLIENT_KEYS", set())}
    for doc in doc_paths:
        rel_doc = os.path.relpath(doc, root).replace(os.sep, "/")
        try:
            with open(doc, "r", encoding="utf-8") as fh:
                doc_lines = fh.read().splitlines()
        except OSError:
            continue
        for lineno, line in enumerate(doc_lines, start=1):
            for m in _DOC_MENTION_RE.finditer(line):
                section, key = m.group(1), m.group(2)
                known = doc_keys[section]
                if known and key not in known and \
                        key not in _STRUCTURAL:
                    findings.append(Finding(
                        RULE, rel_doc, lineno,
                        f"doc mentions `{section}.{key}` but the schema "
                        "does not know that key",
                        hint="the knob was renamed or dropped — update "
                             "the doc or restore the schema key"))

    # 4. RUNBOOK must document the operator knobs
    if os.path.exists(runbook_path):
        rel_rb = os.path.relpath(runbook_path, root).replace(os.sep, "/")
        with open(runbook_path, "r", encoding="utf-8") as fh:
            runbook = fh.read()
        server_keys = sets.get("SERVER_KEYS", set())
        client_keys = sets.get("CLIENT_KEYS", set())
        dataset_keys = sets.get("DATASET_KEYS", set())
        # nested blocks participate too: chaos.infra is an operator
        # knob even though "infra" is a CHAOS_KEYS member, not a
        # top-level section key
        chaos_keys = sets.get("CHAOS_KEYS", set())
        for knob in documented_knobs:
            if knob not in (server_keys | client_keys | dataset_keys |
                            chaos_keys):
                continue  # rule 1/2 territory, do not double-report
            if knob not in runbook:
                findings.append(Finding(
                    RULE, rel_rb, 1,
                    f"operator knob `{knob}` is in the schema but not "
                    "documented in the runbook",
                    hint="add a 'TPU knobs that matter' entry — "
                         "undocumented knobs desync from operating "
                         "practice"))
    return findings
