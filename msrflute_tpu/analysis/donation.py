"""donation-aliasing — reads of donated buffers after dispatch.

``jax.jit(..., donate_argnums=...)`` hands the argument's buffer to XLA:
after the (async) dispatch the old array is logically dead, and reading
it from host code races the in-place program — the torn-buffer class of
bug PR 1's checkpoint-before-donation ordering dodged by hand
(``engine/server.py``: the pending chunk's ``latest`` save is submitted
BEFORE the next dispatch donates the state buffers).

The checker tracks, per module:

1. bindings created from ``jax.jit(fn, donate_argnums=(...))`` (names
   and ``self.<attr>``s), remembering the donated positions — keyword
   ``donate_argnames`` is flagged as unanalyzable rather than ignored;
2. per function scope, calls through those bindings: the argument
   expressions at donated positions (bare or dotted names) become dead;
3. any later read of a dead name in the same scope — before a
   rebinding clears it — is a finding.

Scope-local and flow-naive by design (no branch joins): a read after a
donation in straight-line order is a bug in every execution that
reaches it.  Loop bodies are safe because the donating statement
normally also rebinds the name (``state = step(state, ...)``), which
clears deadness in statement order.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo, call_name, dotted_name

RULE = "donation-aliasing"

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            if isinstance(kw.value, ast.Tuple):
                vals = []
                for elt in kw.value.elts:
                    if not (isinstance(elt, ast.Constant) and
                            isinstance(elt.value, int)):
                        return None
                    vals.append(elt.value)
                return tuple(vals)
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, int):
                return (kw.value.value,)
            return None
    return None


def _collect_donating_bindings(tree: ast.Module, info: ModuleInfo,
                               findings: List[Finding]):
    """{binding name: donated positions} for jit-with-donation results;
    ``self.x`` bindings are keyed as ``"self.x"``."""
    donors: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not (isinstance(value, ast.Call) and
                call_name(value) in _JIT_NAMES):
            continue
        if any(kw.arg == "donate_argnames" for kw in value.keywords):
            findings.append(Finding(
                RULE, info.path, value.lineno,
                "donate_argnames is not analyzable by position",
                hint="use donate_argnums so fluteguard can track the "
                     "donated bindings"))
            continue
        pos = _donated_positions(value)
        if not pos:
            continue
        for tgt in node.targets:
            name = dotted_name(tgt)
            if name is not None:
                donors[name] = pos
    return donors


class _ScopeWalk(ast.NodeVisitor):
    def __init__(self, info: ModuleInfo, donors: Dict[str, Tuple[int, ...]],
                 findings: List[Finding]):
        self.info = info
        self.donors = donors
        self.findings = findings
        #: {dead binding: line of the donating call}
        self.dead: Dict[str, int] = {}

    def _clear(self, target: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._clear(elt)
            return
        name = dotted_name(target)
        if name is None:
            return
        # rebinding `state` also revives `state.params` etc.
        for dead in [d for d in self.dead
                     if d == name or d.startswith(name + ".")]:
            del self.dead[dead]

    def _check_read(self, node: ast.AST) -> None:
        name = dotted_name(node)
        if name is None:
            return
        parts = name.split(".")
        for i in range(1, len(parts) + 1):
            prefix = ".".join(parts[:i])
            if prefix in self.dead:
                self.findings.append(Finding(
                    RULE, self.info.path, node.lineno,
                    f"`{name}` is read after its buffer was donated to "
                    f"the dispatch at line {self.dead[prefix]}",
                    hint="copy what you need BEFORE the donating call "
                         "(jnp.copy / checkpoint submit), or rebind the "
                         "name from the call's result"))
                return

    def visit_Call(self, node: ast.Call) -> None:
        # arguments are read before the donation takes effect
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)
        # a method call READS its receiver (`self.table.sum()` touches
        # the donated table just as surely as a bare load)
        if isinstance(node.func, ast.Attribute):
            self._check_read(node.func.value)
        name = call_name(node)
        if name in self.donors:
            for pos in self.donors[name]:
                if pos < len(node.args):
                    donated = dotted_name(node.args[pos])
                    if donated is not None:
                        self.dead[donated] = node.lineno

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self._check_read(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load) and \
                dotted_name(node) is not None:
            self._check_read(node)
        else:
            # non-name base (e.g. ``f(x).attr``): recurse so the call
            # inside is still seen
            self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        for tgt in node.targets:
            self._clear(tgt)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        self._check_read(node.target)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested scopes walked separately

    visit_AsyncFunctionDef = visit_FunctionDef


def check(info: ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    donors = _collect_donating_bindings(info.tree, info, findings)
    if not donors:
        return findings
    for node in ast.walk(info.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walker = _ScopeWalk(info, donors, findings)
            for stmt in node.body:
                walker.visit(stmt)
    return findings
