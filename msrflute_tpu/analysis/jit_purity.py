"""jit-purity — side effects inside traced function bodies.

A function handed to ``jax.jit`` / ``shard_map`` / ``jax.vmap`` /
``jax.lax.scan`` / ``pl.pallas_call`` runs ONCE at trace time; its
Python side effects do not re-execute per call, and host-state reads
(`time.time()`, ``np.random``) bake a single stale value into the
compiled program.  Both are classic silent-wrongness bugs: the program
"works" and the effect/entropy is simply absent from round 2 onward.

Flagged inside traced bodies (and any function they call, resolved
through the PROJECT call graph since flint v2 — a helper imported from
another module is traced context too, reported in its own file):

- wall-clock reads: ``time.time/perf_counter/monotonic``,
  ``datetime.now``;
- host RNG: ``np.random.*`` / ``random.*`` (use ``jax.random`` with an
  explicit key);
- I/O: ``open``, ``os.remove/replace/rename/makedirs``, ``print``,
  logging sinks (effects belong outside the trace; use
  ``jax.debug.print`` / ``io_callback`` when output is really needed);
- mutation of enclosing object state: assignment/augassign to a
  ``self.*`` target, ``global`` / ``nonlocal`` declarations.

Traced roots come from the module summaries: named function arguments
to the trace entry points, including decorator form (``@jax.jit``),
``functools.partial(fn, ...)`` wrapping, and method bindings
(``self._step = jax.jit(self._body)``); closure follows
``Project.traced_reachable()`` (cross-module chains, cycles, method
dispatch).  A *deliberate* trace-time effect (e.g. recording a slot
table the host decodes with) takes an inline
``# flint: disable=jit-purity <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (Finding, ModuleInfo, Project, build_project,
                   call_name, dotted_name, function_nodes)

RULE = "jit-purity"

_IMPURE_CALLS = {
    "time.time": "wall-clock read bakes ONE trace-time value into the "
                 "compiled program",
    "time.perf_counter": "wall-clock read inside a traced body",
    "time.monotonic": "wall-clock read inside a traced body",
    "time.sleep": "sleeping inside a traced body only delays tracing",
    "datetime.now": "wall-clock read inside a traced body",
    "datetime.datetime.now": "wall-clock read inside a traced body",
    "open": "file I/O inside a traced body runs once, at trace time",
    "os.remove": "filesystem mutation inside a traced body",
    "os.replace": "filesystem mutation inside a traced body",
    "os.rename": "filesystem mutation inside a traced body",
    "os.makedirs": "filesystem mutation inside a traced body",
    "print": "print() inside a traced body fires once at trace time",
}
_IMPURE_PREFIXES = {
    "np.random.": "host RNG inside a traced body — one draw at trace "
                  "time, frozen thereafter; thread a jax.random key",
    "numpy.random.": "host RNG inside a traced body; thread a "
                     "jax.random key",
    "random.": "host RNG inside a traced body; thread a jax.random key",
    "logging.": "logging inside a traced body fires once at trace time",
    "logger.": "logging inside a traced body fires once at trace time",
}


def _named_function_args(call: ast.Call) -> List[str]:
    """Function names passed (positionally or via partial) to a trace
    entry point (shared with pallas-shape's kernel discovery)."""
    from .core import dotted_name as _dn
    out: List[str] = []
    for arg in call.args:
        name = _dn(arg)
        if name is not None:
            out.append(name)
        elif isinstance(arg, ast.Call) and call_name(arg) in (
                "functools.partial", "partial"):
            inner = arg.args and _dn(arg.args[0])
            if inner:
                out.append(inner)
    return out


def _own_body_nodes(fn: ast.AST) -> List[ast.AST]:
    """All nodes of ``fn`` excluding nested function subtrees — nested
    defs are analyzed on their own when they are traced/reached, so
    walking them here would double-report."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _check_body(fn: ast.AST, info: ModuleInfo,
                findings: List[Finding]) -> None:
    for node in _own_body_nodes(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _IMPURE_CALLS:
                findings.append(Finding(
                    RULE, info.path, node.lineno,
                    f"`{name}(...)` in traced `{fn.name}`: "
                    f"{_IMPURE_CALLS[name]}",
                    hint="hoist the effect out of the traced body (or "
                         "jax.debug.print / io_callback for output)"))
            elif name:
                for prefix, why in _IMPURE_PREFIXES.items():
                    if name.startswith(prefix):
                        findings.append(Finding(
                            RULE, info.path, node.lineno,
                            f"`{name}(...)` in traced `{fn.name}`: {why}",
                            hint="hoist the effect out of the traced "
                                 "body"))
                        break
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                base = tgt
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                root = dotted_name(tgt) or (
                    dotted_name(tgt.value) if isinstance(
                        tgt, ast.Subscript) else None)
                if isinstance(base, ast.Name) and base.id == "self" and \
                        not isinstance(tgt, ast.Name):
                    findings.append(Finding(
                        RULE, info.path, node.lineno,
                        f"traced `{fn.name}` mutates `{root or 'self'}` — "
                        "runs once at trace time, not per call",
                        hint="thread the value through the function's "
                             "return instead, or suppress with a reason "
                             "if the trace-time effect is the point"))
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            findings.append(Finding(
                RULE, info.path, node.lineno,
                f"traced `{fn.name}` declares "
                f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                f" {', '.join(node.names)} — trace-time-only mutation",
                hint="return the value instead of mutating outer state"))


def check(info: ModuleInfo,
          project: Optional[Project] = None) -> List[Finding]:
    if project is None:
        # standalone use (unit tests, direct checker calls): a
        # single-module project reproduces the pre-v2 behavior.  The
        # project root is recovered so the summary's rel path matches
        # ``info.path`` exactly.
        root = info.abspath[: -len(info.path)] if \
            info.abspath.replace("\\", "/").endswith(info.path) else "."
        project = build_project(root or ".", [info.abspath],
                                infos={info.path: info})
    reached = project.traced_reachable()
    mine = sorted(q for (m, q) in reached if m == info.path)
    if not mine:
        return []
    nodes = function_nodes(info)
    findings: List[Finding] = []
    for qual in mine:
        fn = nodes.get(qual)
        if fn is not None:
            _check_body(fn, info, findings)
    return findings
