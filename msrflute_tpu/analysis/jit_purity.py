"""jit-purity — side effects inside traced function bodies.

A function handed to ``jax.jit`` / ``shard_map`` / ``jax.vmap`` /
``jax.lax.scan`` / ``pl.pallas_call`` runs ONCE at trace time; its
Python side effects do not re-execute per call, and host-state reads
(`time.time()`, ``np.random``) bake a single stale value into the
compiled program.  Both are classic silent-wrongness bugs: the program
"works" and the effect/entropy is simply absent from round 2 onward.

Flagged inside traced bodies (and same-module functions they call,
transitively):

- wall-clock reads: ``time.time/perf_counter/monotonic``,
  ``datetime.now``;
- host RNG: ``np.random.*`` / ``random.*`` (use ``jax.random`` with an
  explicit key);
- I/O: ``open``, ``os.remove/replace/rename/makedirs``, ``print``,
  logging sinks (effects belong outside the trace; use
  ``jax.debug.print`` / ``io_callback`` when output is really needed);
- mutation of enclosing object state: assignment/augassign to a
  ``self.*`` target, ``global`` / ``nonlocal`` declarations.

Traced roots are resolved same-module only: named function arguments
to the trace entry points, including decorator form (``@jax.jit``) and
``functools.partial(fn, ...)`` wrapping.  A *deliberate* trace-time
effect (e.g. recording a slot table the host decodes with) takes an
inline ``# flint: disable=jit-purity <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, ModuleInfo, call_name, dotted_name

RULE = "jit-purity"

_TRACE_ENTRY = {"jax.jit", "jit", "jax.pmap", "pmap", "shard_map",
                "jax.experimental.shard_map.shard_map", "jax.vmap", "vmap",
                "jax.lax.scan", "lax.scan", "jax.lax.while_loop",
                "lax.while_loop", "jax.lax.fori_loop", "lax.fori_loop",
                "jax.lax.cond", "lax.cond", "jax.checkpoint", "jax.remat",
                "pl.pallas_call", "pallas_call", "jax.grad",
                "jax.value_and_grad"}

_IMPURE_CALLS = {
    "time.time": "wall-clock read bakes ONE trace-time value into the "
                 "compiled program",
    "time.perf_counter": "wall-clock read inside a traced body",
    "time.monotonic": "wall-clock read inside a traced body",
    "time.sleep": "sleeping inside a traced body only delays tracing",
    "datetime.now": "wall-clock read inside a traced body",
    "datetime.datetime.now": "wall-clock read inside a traced body",
    "open": "file I/O inside a traced body runs once, at trace time",
    "os.remove": "filesystem mutation inside a traced body",
    "os.replace": "filesystem mutation inside a traced body",
    "os.rename": "filesystem mutation inside a traced body",
    "os.makedirs": "filesystem mutation inside a traced body",
    "print": "print() inside a traced body fires once at trace time",
}
_IMPURE_PREFIXES = {
    "np.random.": "host RNG inside a traced body — one draw at trace "
                  "time, frozen thereafter; thread a jax.random key",
    "numpy.random.": "host RNG inside a traced body; thread a "
                     "jax.random key",
    "random.": "host RNG inside a traced body; thread a jax.random key",
    "logging.": "logging inside a traced body fires once at trace time",
    "logger.": "logging inside a traced body fires once at trace time",
}


def _named_function_args(call: ast.Call) -> List[str]:
    """Function names passed (positionally or via partial) to a trace
    entry point."""
    out: List[str] = []
    for arg in call.args:
        name = dotted_name(arg)
        if name is not None:
            out.append(name)
        elif isinstance(arg, ast.Call) and call_name(arg) in (
                "functools.partial", "partial"):
            inner = arg.args and dotted_name(arg.args[0])
            if inner:
                out.append(inner)
    return out


def _collect_traced_roots(tree: ast.Module) -> Set[str]:
    """Function names that reach a trace entry point in this module."""
    roots: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_name(node) in _TRACE_ENTRY:
            roots.update(_named_function_args(node))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dec_call = dec.func if isinstance(dec, ast.Call) else dec
                if dotted_name(dec_call) in _TRACE_ENTRY:
                    roots.add(node.name)
    return roots


def _function_index(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """Every (possibly nested) def in the module by bare name — last
    definition wins, which matches runtime shadowing."""
    index: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            index[node.name] = node
    return index


def _called_names(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name and "." not in name:
                out.add(name)
    return out


def _expand_reachable(roots: Set[str],
                      index: Dict[str, ast.FunctionDef]) -> Set[str]:
    seen: Set[str] = set()
    frontier = [r for r in roots if r in index]
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        for callee in _called_names(index[name]):
            if callee in index and callee not in seen:
                frontier.append(callee)
    return seen


def _own_body_nodes(fn: ast.FunctionDef) -> List[ast.AST]:
    """All nodes of ``fn`` excluding nested function subtrees — nested
    defs are analyzed on their own when they are traced/reached, so
    walking them here would double-report."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _check_body(fn: ast.FunctionDef, info: ModuleInfo,
                findings: List[Finding]) -> None:
    for node in _own_body_nodes(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _IMPURE_CALLS:
                findings.append(Finding(
                    RULE, info.path, node.lineno,
                    f"`{name}(...)` in traced `{fn.name}`: "
                    f"{_IMPURE_CALLS[name]}",
                    hint="hoist the effect out of the traced body (or "
                         "jax.debug.print / io_callback for output)"))
            elif name:
                for prefix, why in _IMPURE_PREFIXES.items():
                    if name.startswith(prefix):
                        findings.append(Finding(
                            RULE, info.path, node.lineno,
                            f"`{name}(...)` in traced `{fn.name}`: {why}",
                            hint="hoist the effect out of the traced "
                                 "body"))
                        break
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                base = tgt
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                root = dotted_name(tgt) or (
                    dotted_name(tgt.value) if isinstance(
                        tgt, ast.Subscript) else None)
                if isinstance(base, ast.Name) and base.id == "self" and \
                        not isinstance(tgt, ast.Name):
                    findings.append(Finding(
                        RULE, info.path, node.lineno,
                        f"traced `{fn.name}` mutates `{root or 'self'}` — "
                        "runs once at trace time, not per call",
                        hint="thread the value through the function's "
                             "return instead, or suppress with a reason "
                             "if the trace-time effect is the point"))
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            findings.append(Finding(
                RULE, info.path, node.lineno,
                f"traced `{fn.name}` declares "
                f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                f" {', '.join(node.names)} — trace-time-only mutation",
                hint="return the value instead of mutating outer state"))


def check(info: ModuleInfo) -> List[Finding]:
    roots = _collect_traced_roots(info.tree)
    if not roots:
        return []
    index = _function_index(info.tree)
    findings: List[Finding] = []
    for name in sorted(_expand_reachable(roots, index)):
        _check_body(index[name], info, findings)
    return findings
