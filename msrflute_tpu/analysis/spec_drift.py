"""spec-drift — the pool's sharding spec, consistent end to end.

Fleet mode's whole HBM story is one line: the page pool's slot axis is
SHARDED over the clients mesh axis (``parallel.sharding.
slot_pool_sharding`` = ``NamedSharding(mesh, P(CLIENTS_AXIS))``), so
per-device pool bytes, page-in slices and writeback fetches are all
``total / mesh_size``.  The failure mode is quiet: a replicated spec
still RUNS — every device just carries (and every transfer moves) the
whole pool, an x``mesh_size`` regression no test notices on a 1-device
CI mesh.  This rule pins the spec statically, in ``engine/``:

- **replicated pool binding** — a ``NamedSharding(mesh, P())`` (or
  ``replicated_sharding(mesh)``) bound to a pool/rows/slots/table
  name, including ``self._pool_spec = ...`` attribute bindings;
- **replicated pool put** — ``device_put`` of a pool-named value whose
  spec argument is replicated, constructed inline or resolved through
  a named binding.  When the module ALSO binds a clients-sharded spec,
  the message calls out the drift — the table was annotated
  ``P(CLIENTS_AXIS)`` somewhere and reached a dispatch site built
  ``P()``;
- **unsharded pool put** — ``device_put`` of a pool-named value with
  NO sharding argument at all: the table lands wherever jax defaults
  it (device 0, replicated under jit), invisible to the mesh.

Subsumes and extends shard-ready's replicated-pool check (moved here
so the pool-spec story lives under one rule id).  The sharded idiom —
``slot_pool_sharding`` / ``P(CLIENTS_AXIS)`` — stays silent, as does
everything outside ``engine/`` (model-parallel specs in ``parallel/``
legitimately replicate small leaves).

Facts come from the mesh fact layer (``ModuleSummary.spec_bindings`` /
``device_puts``); no re-parse.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .core import (Finding, ModuleInfo, Project, compute_module_summary,
                   pool_name)

RULE = "spec-drift"


def check(info: ModuleInfo,
          project: Optional[Project] = None) -> List[Finding]:
    if "engine" not in info.path.split("/"):
        return []
    summary = project.modules.get(info.path) if project else None
    if summary is None:
        summary = compute_module_summary(info)
    findings: List[Finding] = []

    kinds: Dict[str, str] = {}       # bound name -> last spec kind
    has_clients_binding = False
    for name, kind, line in summary.spec_bindings:
        kinds[name] = kind
        # both `pool_spec = ...` and `self._pool_spec = ...` resolve a
        # later bare/attr reference
        kinds[name.rsplit(".", 1)[-1]] = kind
        if kind == "clients":
            has_clients_binding = True
        if kind == "replicated" and pool_name(name):
            findings.append(Finding(
                RULE, info.path, line,
                f"slot-axis table spec `{name}` is a REPLICATED "
                "NamedSharding — the page pool's slot axis must shard "
                "over the clients mesh axis",
                hint="use parallel.sharding.slot_pool_sharding "
                     "(P(CLIENTS_AXIS) on axis 0): per-device pool HBM "
                     "and page-in/writeback bytes become "
                     "total/mesh_size instead of xmesh_size"))

    for target, desc, line, _qual in summary.device_puts:
        if not pool_name(target.split("(")[0].split("[")[0]):
            continue
        kind = desc
        if desc.startswith("name:"):
            ref = desc.split(":", 1)[1]
            kind = kinds.get(ref, kinds.get(ref.rsplit(".", 1)[-1], ""))
        if kind == "replicated":
            drift = (" — the module binds a clients-sharded spec "
                     "elsewhere, so this dispatch site drifted from "
                     "the table's annotation") if has_clients_binding \
                else ""
            findings.append(Finding(
                RULE, info.path, line,
                f"device_put of slot-axis table `{target}` with a "
                "replicated sharding — every device receives the whole "
                f"pool buffer (bytes x mesh_size){drift}",
                hint="stage pool rows with slot_pool_sharding "
                     "(P(CLIENTS_AXIS)): each device then receives "
                     "only its shard's segment, total/mesh_size bytes"))
        elif desc == "none":
            findings.append(Finding(
                RULE, info.path, line,
                f"device_put of slot-axis table `{target}` with NO "
                "sharding — the table lands replicated/on device 0, "
                "invisible to the mesh layout",
                hint="pass the pool's sharding explicitly "
                     "(slot_pool_sharding(mesh)); an unsharded put is "
                     "how the replicated-pool regression ships"))
    return findings
