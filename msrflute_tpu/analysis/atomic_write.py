"""atomic-write — durable artifacts land whole or not at all.

A checkpoint, scorecard, baseline or status log is read by a DIFFERENT
process epoch than the one that wrote it (resume after preemption, the
scope diff gate, the tier-1 lint gate).  A bare ``open(path, "w")``
truncates the only copy first and fills it back byte by byte — a crash
(or the PR 3 chaos harness's injected IO fault) anywhere in that window
leaves a torn artifact that fails checksum verification at best and
parses as garbage at worst.  The shipped recipes:

- **tmp + replace** — write ``path + ".tmp"`` completely, then
  ``os.replace(tmp, path)``: the committed generation is never opened
  for writing (``utils/io.py::update_json_log``, the scorecard, the
  trace writer, the checkpoint ``_write_blob``).
- **hardlink rotation** — promoting ``latest`` to ``.prev`` goes
  ``os.link(src, lnk); os.replace(lnk, dst)`` so the committed slot
  never disappears; a bare ``os.rename(latest, latest + ".prev")``
  opens a crash instant with ZERO loadable slots (the PR 3 crash-window
  class).

Flagged, package-wide: ``open(…, "w"/"wb")`` — and ``os.rename``/
``os.replace`` SOURCES — whose path text (one level of local-variable
provenance deep) names a durable artifact and is not a scratch name
(``.tmp``/``.new``/``.part``/``.lnk``…).  Append-mode streams
(``events.jsonl``, ``metrics.jsonl``) are incremental by design and
stay silent; so do writes to paths the rule cannot prove durable —
the runtime chaos/IO-fault tests are the backstop there.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List

from .core import Finding, ModuleInfo, call_name, open_mode

RULE = "atomic-write"

#: path text that denotes a durable artifact.  Artifact-ish tokens
#: only — a bare directory variable (`model_dir`, `out_dir`) must not
#: mark every file written under it durable; `ckpt`/`checkpoint` DO
#: stay in the set because anything placed in the checkpoint tree is
#: resume territory.
_DURABLE_RE = re.compile(
    r"status_log|scorecard|baseline|checkpoint|ckpt|latest|best_val|"
    r"model_name|msgpack|\.ptr\b|sidecar|\.sum\b|stats_name|trace\.json",
    re.I)
#: path text that denotes the scratch half of an atomic idiom (or a
#: cache nobody resumes from)
_SCRATCH_RE = re.compile(r"tmp|\.new\b|\.part\b|lnk|scratch|cache", re.I)

_HINT_WRITE = ("write the full content to `path + \".tmp\"` and "
               "`os.replace(tmp, path)` — the committed copy is never "
               "open for writing (utils/io.py update_json_log is the "
               "shared recipe); append-only streams use mode \"a\"")
_HINT_RENAME = ("rotate by hardlink so the committed slot never "
                "disappears: os.link(src, lnk); os.replace(lnk, dst) "
                "(checkpoint._write_blob's _rotate), or write the new "
                "generation to tmp and os.replace over the old")


def _path_text(node: ast.AST, local_assigns: Dict[str, str],
               depth: int = 3) -> str:
    """Source text of a path expression, following bare local names
    through their assignments a few levels deep."""
    try:
        src = ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""
    seen = 0
    while depth > seen and re.fullmatch(r"[A-Za-z_]\w*", src.strip()):
        provenance = local_assigns.get(src.strip())
        if provenance is None:
            break
        src = provenance
        seen += 1
    return src


def check(info: ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []

    def walk(node: ast.AST, local_assigns: Dict[str, str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                walk(child, {})  # fresh local scope
                continue
            if isinstance(child, ast.Assign) and \
                    len(child.targets) == 1 and \
                    isinstance(child.targets[0], ast.Name):
                try:
                    local_assigns[child.targets[0].id] = \
                        ast.unparse(child.value)
                except Exception:  # pragma: no cover
                    pass
            if isinstance(child, ast.Call):
                _check_call(child, local_assigns)
            walk(child, local_assigns)

    def _check_call(node: ast.Call,
                    local_assigns: Dict[str, str]) -> None:
        name = call_name(node)
        if name == "open" and node.args:
            mode = open_mode(node)
            if mode is None or "w" not in mode or "a" in mode:
                return  # reads and append streams are fine
            text = _path_text(node.args[0], local_assigns)
            if _SCRATCH_RE.search(text) or not _DURABLE_RE.search(text):
                return
            findings.append(Finding(
                RULE, info.path, node.lineno,
                f"bare open({text!r}, {mode!r}) on a durable artifact "
                "truncates the committed copy before the new content "
                "is complete — a crash mid-write leaves a torn file",
                hint=_HINT_WRITE))
        elif name in ("os.rename", "os.replace") and len(node.args) >= 2:
            src_text = _path_text(node.args[0], local_assigns)
            if _SCRATCH_RE.search(src_text) or \
                    not _DURABLE_RE.search(src_text):
                return
            findings.append(Finding(
                RULE, info.path, node.lineno,
                f"{name}({src_text!r}, …) moves the committed durable "
                "copy away — between this and the replacement landing "
                "there is a crash instant with no loadable slot at all",
                hint=_HINT_RENAME))

    walk(info.tree, {})
    return findings
