"""Typed configuration tree for msrflute_tpu.

Parity target: reference ``core/config.py`` (dataclass tree with
``MutableMapping`` dict-compat and dotted ``lookup``, ``core/config.py:39-79``)
plus ``core/schema.py`` (cerberus schema).  We keep FLUTE's six top-level
sections and key vocabulary (``doc/sphinx/scenarios.rst:137-145``) so that
reference YAML configs translate mechanically:

    model_config, dp_config, privacy_metrics_config, strategy,
    server_config, client_config

Differences from the reference, by design:

- Validation is a hand-rolled schema (:mod:`msrflute_tpu.schema`) rather than
  cerberus — the reference loads its schema with ``eval(open(...))``
  (``core/config.py:766-769``); we use an importable module.
- Unknown keys are preserved in an ``extra`` mapping on each section instead
  of being dropped, because task plugins read free-form model parameters.
"""

from __future__ import annotations

import copy
import dataclasses
import os
from collections.abc import MutableMapping
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml


class Config(MutableMapping):
    """Dict-compatible config base with dotted-path ``lookup``.

    Mirrors the ergonomics of reference ``core/config.py:39-79``: sections
    behave both as attributes and as mapping items, and
    ``cfg.lookup('server_config.optimizer_config.lr')`` resolves nested keys,
    returning ``default`` when any component is missing.
    """

    def lookup(self, path: str, default: Any = None) -> Any:
        node: Any = self
        for part in path.split("."):
            if node is None:
                return default
            if isinstance(node, MutableMapping) or dataclasses.is_dataclass(node):
                try:
                    node = node[part] if isinstance(node, MutableMapping) else getattr(node, part)
                except (KeyError, AttributeError):
                    return default
            elif isinstance(node, dict):
                node = node.get(part, default)
            else:
                node = getattr(node, part, None)
                if node is None:
                    return default
        return default if node is None else node

    # MutableMapping protocol over dataclass fields + extras ------------
    def _field_names(self) -> List[str]:
        return [f.name for f in dataclasses.fields(self)]  # type: ignore[arg-type]

    def __getitem__(self, key: str) -> Any:
        if key in self._field_names():
            return getattr(self, key)
        extra = getattr(self, "extra", None)
        if extra is not None and key in extra:
            return extra[key]
        raise KeyError(key)

    def __setitem__(self, key: str, value: Any) -> None:
        if key in self._field_names():
            setattr(self, key, value)
        else:
            getattr(self, "extra")[key] = value

    def __delitem__(self, key: str) -> None:
        if key in self._field_names():
            setattr(self, key, None)
        else:
            del getattr(self, "extra")[key]

    def __iter__(self):
        for name in self._field_names():
            if name != "extra" and getattr(self, name) is not None:
                yield name
        for key in getattr(self, "extra", {}):
            yield key

    def __len__(self) -> int:
        return sum(1 for _ in self)

    def get(self, key: str, default: Any = None) -> Any:
        try:
            value = self[key]
        except KeyError:
            return default
        return default if value is None else value

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key in self:
            value = self[key]
            out[key] = value.to_dict() if isinstance(value, Config) else copy.deepcopy(value)
        return out


def _take(raw: Dict[str, Any], known: List[str]) -> Dict[str, Any]:
    """Split ``raw`` into kwargs for known fields; the rest goes to extra."""
    kwargs = {k: raw[k] for k in known if k in raw}
    kwargs["extra"] = {k: copy.deepcopy(v) for k, v in raw.items() if k not in known}
    return kwargs


@dataclass
class OptimizerConfig(Config):
    """Optimizer settings (reference ``core/config.py`` OptimizerConfig;
    allowed types from ``core/schema.py:90``)."""

    type: str = "sgd"
    lr: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    weight_decay: float = 0.0
    amsgrad: bool = False
    eps: float = 1e-8
    betas: Optional[List[float]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "OptimizerConfig":
        if raw is None:
            return cls()
        return cls(**_take(dict(raw), [
            "type", "lr", "momentum", "nesterov", "weight_decay", "amsgrad",
            "eps", "betas"]))


@dataclass
class AnnealingConfig(Config):
    """LR-annealing settings (reference ``utils/utils.py:151-224``)."""

    type: str = "step_lr"
    step_interval: str = "epoch"
    step_size: int = 1
    gamma: float = 1.0
    milestones: Optional[List[int]] = None
    # val_loss / ReduceLROnPlateau mode:
    patience: int = 10
    factor: float = 0.1
    # rampup-keep-expdecay-keep schedule:
    peak_lr: Optional[float] = None
    floor_lr: Optional[float] = None
    rampup_steps: int = 0
    hold_steps: int = 0
    decay_steps: int = 1
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "AnnealingConfig":
        if raw is None:
            return cls()
        return cls(**_take(dict(raw), [
            "type", "step_interval", "step_size", "gamma", "milestones",
            "patience", "factor", "peak_lr", "floor_lr", "rampup_steps",
            "hold_steps", "decay_steps"]))


@dataclass
class DatasetConfig(Config):
    """One split's data settings (reference DataConfig per-split blocks)."""

    batch_size: int = 32
    loader_type: str = "auto"
    list_of_train_data: Optional[str] = None
    test_data: Optional[str] = None
    val_data: Optional[str] = None
    train_data: Optional[str] = None
    train_data_server: Optional[str] = None
    vocab_dict: Optional[str] = None
    pin_memory: bool = True
    num_workers: int = 0
    desired_max_samples: Optional[int] = None
    max_batch_size: Optional[int] = None
    max_num_words: Optional[int] = None
    max_seq_length: Optional[int] = None
    min_words_per_utt: Optional[int] = None
    num_frames: Optional[int] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "DatasetConfig":
        if raw is None:
            return cls()
        return cls(**_take(dict(raw), [
            "batch_size", "loader_type", "list_of_train_data", "test_data",
            "val_data", "train_data", "train_data_server", "vocab_dict",
            "pin_memory", "num_workers", "desired_max_samples",
            "max_batch_size", "max_num_words", "max_seq_length",
            "min_words_per_utt", "num_frames"]))


@dataclass
class DataConfig(Config):
    """train/val/test dataset triple (reference DataConfig)."""

    train: DatasetConfig = field(default_factory=DatasetConfig)
    val: DatasetConfig = field(default_factory=DatasetConfig)
    test: DatasetConfig = field(default_factory=DatasetConfig)
    num_clients: Optional[int] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "DataConfig":
        if raw is None:
            return cls()
        raw = dict(raw)
        return cls(
            train=DatasetConfig.from_dict(raw.pop("train", None)),
            val=DatasetConfig.from_dict(raw.pop("val", None)),
            test=DatasetConfig.from_dict(raw.pop("test", None)),
            num_clients=raw.pop("num_clients", None),
            extra=raw,
        )


@dataclass
class ModelConfig(Config):
    """Model selection + free-form model params (reference ModelConfig).

    ``model_type`` names a class in the task plugin's ``model.py``
    (reference ``doc/sphinx/scenarios.rst:96-106``); here it names an entry
    in :mod:`msrflute_tpu.models.registry` or a plugin module.
    """

    model_type: str = "LR"
    model_folder: Optional[str] = None
    pretrained_model_path: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "ModelConfig":
        if raw is None:
            return cls()
        return cls(**_take(dict(raw), [
            "model_type", "model_folder", "pretrained_model_path"]))


@dataclass
class DPConfig(Config):
    """Differential-privacy settings (reference ``core/schema.py`` dp_config
    block; consumed by ``extensions/privacy/__init__.py:128-201``)."""

    enable_local_dp: bool = False
    enable_global_dp: bool = False
    eps: float = -1.0            # local epsilon; eps < 0 => clip-only mode
    delta: float = 1e-7
    max_grad: float = 1.0        # L2 clip bound for the flattened update
    max_weight: float = 100.0    # aggregation-weight clip ceiling
    min_weight: float = 0.0
    weight_scaler: float = 1.0   # scale applied to weight before noising
    global_sigma: float = 0.0    # server-side noise multiplier
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "DPConfig":
        if raw is None:
            return cls()
        return cls(**_take(dict(raw), [
            "enable_local_dp", "enable_global_dp", "eps", "delta", "max_grad",
            "max_weight", "min_weight", "weight_scaler", "global_sigma"]))


@dataclass
class PrivacyMetricsConfig(Config):
    """Privacy-attack metric settings (reference privacy_metrics_config,
    consumed at ``core/client.py:466-508``)."""

    apply_metrics: bool = False
    apply_indices_extraction: bool = False
    allowed_word_rank: int = 9000
    apply_leakage_metric: bool = False
    max_leakage: float = 30.0
    max_allowed_leakage: float = 3.0
    adaptive_leakage_threshold: float = 0.0
    is_leakage_weighted: bool = False
    attacker_optimizer_config: OptimizerConfig = field(default_factory=OptimizerConfig)
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "PrivacyMetricsConfig":
        if raw is None:
            return cls()
        raw = dict(raw)
        att = OptimizerConfig.from_dict(raw.pop("attacker_optimizer_config", None))
        out = cls(**_take(raw, [
            "apply_metrics", "apply_indices_extraction", "allowed_word_rank",
            "apply_leakage_metric", "max_leakage", "max_allowed_leakage",
            "adaptive_leakage_threshold", "is_leakage_weighted"]))
        out.attacker_optimizer_config = att
        return out


@dataclass
class ServerReplayConfig(Config):
    """Server-side replay training (reference ServerReplayConfig,
    ``core/server.py:429-442``)."""

    server_iterations: int = 1
    optimizer_config: OptimizerConfig = field(default_factory=OptimizerConfig)
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> Optional["ServerReplayConfig"]:
        if raw is None:
            return None
        raw = dict(raw)
        opt = OptimizerConfig.from_dict(raw.pop("optimizer_config", None))
        out = cls(**_take(raw, ["server_iterations"]))
        out.optimizer_config = opt
        return out


@dataclass
class RLConfig(Config):
    """RL meta-aggregator settings (reference RLConfig, ``extensions/RL``)."""

    marginal_update_RL: bool = True
    RL_path: Optional[str] = None
    RL_path_global: bool = True
    model_descriptor_RL: str = "marginalUpdate"
    network_params: Optional[List[int]] = None
    initial_epsilon: float = 0.5
    final_epsilon: float = 0.0001
    epsilon_gamma: float = 0.90
    max_replay_memory_size: int = 1000
    minibatch_size: int = 16
    gamma: float = 0.99
    optimizer_config: OptimizerConfig = field(default_factory=OptimizerConfig)
    annealing_config: AnnealingConfig = field(default_factory=AnnealingConfig)
    wantLSTM: bool = False
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> Optional["RLConfig"]:
        if raw is None:
            return None
        raw = dict(raw)
        opt = OptimizerConfig.from_dict(raw.pop("optimizer_config", None))
        ann = AnnealingConfig.from_dict(raw.pop("annealing_config", None))
        out = cls(**_take(raw, [
            "marginal_update_RL", "RL_path", "RL_path_global",
            "model_descriptor_RL", "network_params", "initial_epsilon",
            "final_epsilon", "epsilon_gamma", "max_replay_memory_size",
            "minibatch_size", "gamma", "wantLSTM"]))
        out.optimizer_config = opt
        out.annealing_config = ann
        return out


@dataclass
class ServerConfig(Config):
    """Server round-loop settings (reference ServerConfig,
    ``core/server.py:48-181``)."""

    type: str = "optimization"
    max_iteration: int = 100
    num_clients_per_iteration: Any = 10   # int or "lo:hi" random range (core/server.py:284-291)
    initial_lr_client: float = 0.01
    lr_decay_factor: float = 1.0
    val_freq: int = 20
    rec_freq: int = 20
    initial_val: bool = True
    initial_rec: bool = False
    best_model_criterion: str = "loss"
    fall_back_to_best_model: bool = False
    model_backup_freq: int = 100
    resume_from_checkpoint: bool = False
    send_dicts: bool = False
    max_grad_norm: Optional[float] = None
    do_profiling: bool = False
    wantRL: bool = False
    aggregate_median: Optional[str] = None   # 'softmax' => DGA weighting
    softmax_beta: float = 1.0
    initial_lr: float = 0.0
    weight_train_loss: str = "train_loss"
    stale_prob: float = 0.0
    num_skip_decoding: int = -1
    data_config: DataConfig = field(default_factory=DataConfig)
    optimizer_config: OptimizerConfig = field(default_factory=OptimizerConfig)
    annealing_config: AnnealingConfig = field(default_factory=AnnealingConfig)
    server_replay_config: Optional[ServerReplayConfig] = None
    RL: Optional[RLConfig] = None
    nbest_task_scheduler: Optional[Dict[str, Any]] = None
    # TPU-native resilience extensions (no reference equivalent):
    # seeded deterministic fault injection (resilience/chaos.py) and the
    # checkpoint retry/backoff/escalation policy
    # (resilience/integrity.py::RetryPolicy) — both free-form dicts whose
    # keys the schema validates (schema.CHAOS_KEYS /
    # CHECKPOINT_RETRY_KEYS)
    chaos: Optional[Dict[str, Any]] = None
    checkpoint_retry: Optional[Dict[str, Any]] = None
    # flutescope telemetry (telemetry/): spans + trace export, the
    # device-metric bus, opt-in jax.profiler windows, and watchdogs —
    # free-form dict validated by schema.TELEMETRY_KEYS /
    # WATCHDOG_KEYS; absent (the default) means telemetry fully off
    telemetry: Optional[Dict[str, Any]] = None
    # fluteshield screened aggregation (robust/): on-device NaN/Inf +
    # norm-outlier quarantine and Byzantine-robust aggregator variants
    # (strategies/robust.py) — free-form dict validated by
    # schema.ROBUST_KEYS; absent (the default) is the firewall path:
    # the exact pre-fluteshield round program
    robust: Optional[Dict[str, Any]] = None
    # cohort shape-bucketing (engine/round.py + data/batching.py):
    # partition each round's cohort into power-of-two step buckets and
    # dispatch one compact grid per bucket instead of padding every
    # client to the slowest one — free-form dict validated by
    # schema.COHORT_BUCKETING_KEYS; absent (the default) keeps the
    # monolithic [K, S, B] round program
    cohort_bucketing: Optional[Dict[str, Any]] = None
    # megakernel local SGD (engine/client_update.py): epoch/step loop
    # fusion (default on even when the block is absent) and the opt-in
    # pallas fused SGD apply — free-form dict validated by
    # schema.MEGAKERNEL_KEYS; `enable: false` restores the legacy
    # per-epoch unrolled trace for A/Bs
    megakernel: Optional[Dict[str, Any]] = None
    # precision policy (engine/client_update.py): params/compute/stats
    # dtypes for the client inner loop — free-form dict validated by
    # schema.PRECISION_KEYS; absent (the default) is the bit-identical
    # f32 path
    precision: Optional[Dict[str, Any]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "ServerConfig":
        if raw is None:
            return cls()
        raw = dict(raw)
        data = DataConfig.from_dict(raw.pop("data_config", None))
        opt = OptimizerConfig.from_dict(raw.pop("optimizer_config", None))
        ann = AnnealingConfig.from_dict(raw.pop("annealing_config", None))
        replay = ServerReplayConfig.from_dict(raw.pop("server_replay_config", None))
        rl = RLConfig.from_dict(raw.pop("RL", None))
        out = cls(**_take(raw, [
            "type", "max_iteration", "num_clients_per_iteration",
            "initial_lr_client", "lr_decay_factor", "val_freq", "rec_freq",
            "initial_val", "initial_rec", "best_model_criterion",
            "fall_back_to_best_model", "model_backup_freq",
            "resume_from_checkpoint", "send_dicts", "max_grad_norm",
            "do_profiling", "wantRL", "aggregate_median", "softmax_beta",
            "initial_lr", "weight_train_loss", "stale_prob",
            "num_skip_decoding", "nbest_task_scheduler", "chaos",
            "checkpoint_retry", "telemetry", "robust",
            "cohort_bucketing", "megakernel", "precision"]))
        out.data_config = data
        out.optimizer_config = opt
        out.annealing_config = ann
        out.server_replay_config = replay
        out.RL = rl
        return out


@dataclass
class ClientConfig(Config):
    """Client-side settings (reference ClientConfig,
    ``core/client.py:226-511``)."""

    type: str = "optimization"
    meta_learning: str = "basic"
    copying_train_data: bool = False
    do_profiling: bool = False
    ignore_subtask: bool = False
    num_skip_decoding: int = -1
    desired_max_samples: Optional[int] = None
    max_grad_norm: Optional[float] = None
    # per-layer LR freezing (reference core/client.py:306-307)
    freeze_layer: Optional[List[str]] = None
    data_config: DataConfig = field(default_factory=DataConfig)
    optimizer_config: OptimizerConfig = field(default_factory=OptimizerConfig)
    annealing_config: Optional[AnnealingConfig] = None
    # FedProx proximal term mu (reference core/trainer.py:416-501)
    fedprox_mu: float = 0.0
    # personalization (reference core/client.py:387-443, experiments/cv)
    convex_model_interp: Optional[float] = None
    meta_optimizer_config: Optional[OptimizerConfig] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "ClientConfig":
        if raw is None:
            return cls()
        raw = dict(raw)
        data = DataConfig.from_dict(raw.pop("data_config", None))
        opt = OptimizerConfig.from_dict(raw.pop("optimizer_config", None))
        ann_raw = raw.pop("annealing_config", None)
        meta_raw = raw.pop("meta_optimizer_config", None)
        out = cls(**_take(raw, [
            "type", "meta_learning", "copying_train_data", "do_profiling",
            "ignore_subtask", "num_skip_decoding", "desired_max_samples",
            "max_grad_norm", "freeze_layer", "fedprox_mu",
            "convex_model_interp"]))
        out.data_config = data
        out.optimizer_config = opt
        out.annealing_config = AnnealingConfig.from_dict(ann_raw) if ann_raw else None
        out.meta_optimizer_config = OptimizerConfig.from_dict(meta_raw) if meta_raw else None
        return out


@dataclass
class FLUTEConfig(Config):
    """Top-level config (reference FLUTEConfig, ``core/config.py:713-796``).

    Six sections, same vocabulary as the reference
    (``doc/sphinx/scenarios.rst:137-145``).
    """

    model_config: ModelConfig = field(default_factory=ModelConfig)
    dp_config: Optional[DPConfig] = None
    privacy_metrics_config: Optional[PrivacyMetricsConfig] = None
    strategy: str = "fedavg"
    server_config: ServerConfig = field(default_factory=ServerConfig)
    client_config: ClientConfig = field(default_factory=ClientConfig)
    # engine-level (TPU-native additions; no reference equivalent)
    mesh_config: Dict[str, Any] = field(default_factory=dict)
    task: Optional[str] = None
    data_path: Optional[str] = None
    output_path: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, raw: Dict[str, Any], validate_schema: bool = True) -> "FLUTEConfig":
        from . import schema

        raw = copy.deepcopy(raw)
        if validate_schema:
            schema.validate(raw)
        dp_raw = raw.pop("dp_config", None)
        pm_raw = raw.pop("privacy_metrics_config", None)
        out = cls(
            model_config=ModelConfig.from_dict(raw.pop("model_config", None)),
            dp_config=DPConfig.from_dict(dp_raw) if dp_raw is not None else None,
            privacy_metrics_config=(PrivacyMetricsConfig.from_dict(pm_raw)
                                    if pm_raw is not None else None),
            strategy=raw.pop("strategy", "fedavg"),
            server_config=ServerConfig.from_dict(raw.pop("server_config", None)),
            client_config=ClientConfig.from_dict(raw.pop("client_config", None)),
            mesh_config=raw.pop("mesh_config", {}) or {},
            task=raw.pop("task", None),
            data_path=raw.pop("data_path", None),
            output_path=raw.pop("output_path", None),
            extra=raw,
        )
        return out

    @classmethod
    def from_yaml(cls, path: str, **kw: Any) -> "FLUTEConfig":
        with open(path, "r") as fh:
            return cls.from_dict(yaml.safe_load(fh), **kw)

    def validate(self, data_path: Optional[str] = None) -> "FLUTEConfig":
        """Join data paths into the config (reference
        ``core/config.py:736-760`` joins ``data_path`` onto the per-split
        file names) and normalize derived fields."""
        data_path = data_path or self.data_path
        if data_path:
            for section in (self.server_config.data_config, self.client_config.data_config):
                for split in (section.train, section.val, section.test):
                    for attr in ("list_of_train_data", "test_data", "val_data",
                                 "train_data", "train_data_server", "vocab_dict"):
                        val = getattr(split, attr)
                        if val and not os.path.isabs(val):
                            setattr(split, attr, os.path.join(data_path, val))
            vocab = self.model_config.get("vocab_dict")
            if vocab and not os.path.isabs(vocab):
                self.model_config["vocab_dict"] = os.path.join(data_path, vocab)
        return self


def parse_clients_per_round(spec: Any, rng) -> int:
    """Resolve ``num_clients_per_iteration``: an int, or ``"lo:hi"`` meaning
    a per-round uniform random count (reference ``core/server.py:284-291``)."""
    if isinstance(spec, int):
        return spec
    if isinstance(spec, str) and ":" in spec:
        lo, hi = (int(x) for x in spec.split(":"))
        return int(rng.integers(lo, hi + 1))
    return int(spec)


def cohort_upper_bound(spec: Any) -> int:
    """The largest cohort ``num_clients_per_iteration`` can draw — the
    rng-free companion of :func:`parse_clients_per_round` (one parser
    for the ``"lo:hi"`` spec; capacity/pool sizing must never desync
    from the draw's format)."""
    if isinstance(spec, str) and ":" in spec:
        return int(spec.split(":")[1])
    return int(spec)
