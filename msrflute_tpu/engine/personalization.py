"""Personalization server.

Parity target: reference ``experiments/cv/server.py:9-18`` —
``PersonalizationServer`` is a ctor-only subclass hook of
``OptimizationServer`` (the actual personalization math — convex model
interpolation and per-user alpha updates, ``core/client.py:387-443`` and
``utils/utils.py:598-617`` — runs on the client side; see
:mod:`msrflute_tpu.engine.personalization_state`).
"""

from __future__ import annotations

from .server import OptimizationServer


class PersonalizationServer(OptimizationServer):
    """Round loop with per-user personalization state enabled."""
