"""Personalization — per-user local models with convex interpolation.

Parity target: reference personalization flow
(``experiments/cv/server.py``, ``core/client.py:387-443``,
``utils/utils.py:598-617``):

- every user owns a persistent *local* model and a scalar ``alpha``;
- when sampled, the user trains BOTH the global model (the normal federated
  path) and its local model on the same data;
- ``alpha`` takes one SGD step on the interpolation objective:
  ``grad_alpha = sum((w_g - w_p) . (alpha*pg_g + (1-alpha)*pg_p)) + 0.02*alpha``
  with ``alpha`` clipped to [1e-4, 0.9999] (``utils/utils.py:607-617``,
  the reference's argument names are swapped — semantics preserved);
- evaluation interpolates logits: ``alpha*personal + (1-alpha)*global``
  (``convex_inference``, ``utils/utils.py:600-605``), metric = accuracy.

TPU-native: local models of the round's sampled users are stacked on the
clients axis and trained by the SAME vmapped client-update program as the
global pass — one extra shard_map program per round, no per-user Python.
Per-user state lives host-side in :class:`PersonalizationStore` between
rounds (the analogue of the reference's ``<user>_model.tar`` /
``<user>_alpha`` files) and is checkpointed with msgpack.

Divergence (configurable): the reference cold-starts a user's local model
with random init (``make_model``, ``core/client.py:390``); default here is
to clone the current global params (``personalization_init: global``), which
dominates random init; set ``personalization_init: random`` for the
reference behavior.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization
from jax.sharding import NamedSharding, PartitionSpec as P

from ..data.batching import pack_round_batches
from ..parallel.mesh import CLIENTS_AXIS, pad_to_mesh
from ..utils.logging import log_metric, print_rank
from ..utils.metrics import Metric
from .server import OptimizationServer


class PersonalizationStore:
    """Host-side per-user (local_params, alpha) state.

    Persistence mirrors the reference's per-user files
    (``<user>_model.tar`` / ``<user>_alpha``, ``core/client.py:408-443``):
    one msgpack per user, written only when that user was updated — so a
    round's save cost is O(sampled users), not O(all seen users).
    """

    def __init__(self, init_alpha: float, store_dir: Optional[str] = None):
        self.init_alpha = float(init_alpha)
        self.store_dir = store_dir
        self.params: Dict[int, Any] = {}
        self.alpha: Dict[int, float] = {}
        self._dirty: set = set()

    def get(self, user_idx: int, default_params) -> Tuple[Any, float]:
        return (self.params.get(user_idx, default_params),
                self.alpha.get(user_idx, self.init_alpha))

    def put(self, user_idx: int, params: Any, alpha: float) -> None:
        self.params[user_idx] = params
        self.alpha[user_idx] = float(alpha)
        self._dirty.add(user_idx)

    def _user_path(self, uid: int) -> str:
        return os.path.join(self.store_dir, f"user{uid}_model.msgpack")

    def save(self) -> None:
        """Flush users updated since the last save."""
        if self.store_dir is None:
            return
        os.makedirs(self.store_dir, exist_ok=True)
        for uid in self._dirty:
            blob = serialization.msgpack_serialize(serialization.to_state_dict(
                {"alpha": self.alpha[uid],
                 "params": jax.device_get(self.params[uid])}))
            with open(self._user_path(uid), "wb") as fh:
                fh.write(blob)
        self._dirty.clear()

    def load(self, template) -> bool:
        if self.store_dir is None or not os.path.isdir(self.store_dir):
            return False
        tmpl = serialization.to_state_dict(jax.device_get(template))
        found = False
        for name in os.listdir(self.store_dir):
            if not (name.startswith("user") and name.endswith("_model.msgpack")):
                continue
            uid = int(name[len("user"):-len("_model.msgpack")])
            with open(os.path.join(self.store_dir, name), "rb") as fh:
                raw = serialization.msgpack_restore(fh.read())
            self.alpha[uid] = float(raw["alpha"])
            self.params[uid] = serialization.from_state_dict(
                tmpl, raw["params"])
            found = True
        return found


class PersonalizationServer(OptimizationServer):
    """OptimizationServer + per-user personalization passes.

    Two modes: the host path (default) runs a separate jitted personal
    pass per round inside the ``_sample`` hook and keeps per-user state
    in a host-side :class:`PersonalizationStore`; with
    ``server_config.fused_carry: true`` the per-user local models and
    alphas instead ride ``strategy_state`` as device-resident carry
    (``strategies/personalized.py``) — the round pipelines like FedAvg,
    durability rides the model checkpoint, and the personalized eval
    reads the tables back with one explicit fetch at eval boundaries.
    """

    #: under fused_carry the ``_sample`` hook degrades to the base
    #: sampler (the personal pass moved into the round program), so the
    #: server's host-orchestrated predicate must not count it
    fused_carry_sample = True

    def _select_strategy(self, config) -> type:
        if self._fused_carry:
            from ..strategies.personalized import PersonalizedFedAvg
            strat = (config.strategy or "fedavg").lower()
            if strat not in ("fedavg", "fedprox"):
                raise ValueError(
                    f"fused_carry personalization composes only with "
                    f"strategy: fedavg/fedprox (got {strat!r}) — the "
                    "carry tables replace the host store, and other "
                    "strategies keep their own state; drop fused_carry")
            return PersonalizedFedAvg
        return super()._select_strategy(config)

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        cc = self.config.client_config
        self.alpha0 = float(cc.get("convex_model_interp", 0.75))
        if self._fused_carry:
            # device-carry mode: per-user state lives in strategy_state
            # (checkpointed with the model), the personal pass runs
            # inside the fused round program, and there is no host store
            self.store = None
            self._personal_fn = None
            self._personal_eval_fn = None
            self._interp_space = self.config.server_config.get(
                "personalization_interp", "probs")
            return
        self._store_path = os.path.join(self.ckpt.model_dir,
                                        "personalization")
        self.store = PersonalizationStore(self.alpha0, self._store_path)
        if self.config.server_config.get("resume_from_checkpoint", False):
            if self.store.load(self.state.params):
                print_rank(f"restored personalization state for "
                           f"{len(self.store.alpha)} users")
        self._personal_fn = None
        self._personal_eval_fn = None
        init_kind = self.config.server_config.get(
            "personalization_init", "global")
        self._random_init = init_kind == "random"
        # "initial": cold-start local models from the ROUND-0 global
        # weights.  With a pretrained_model_path this is exactly what a
        # reference adapter that loads the seed file in its constructor
        # sees (the reference's own make_model draws a fresh torch-RNG
        # init, core/client.py:390 + experiments/__init__.py:19 — which no
        # cross-framework run can reproduce; the parity harness pins both
        # sides to the seed file instead)
        self._initial_params = (jax.device_get(self.state.params)
                                if init_kind == "initial" else None)
        # interpolation space for the personalized eval: the reference
        # interpolates LOG-probabilities (cv model.py:294 applies
        # LogSoftmax, convex_inference mixes those — a geometric prob
        # mean), while plain "probs" (arithmetic mean, the standard
        # ensemble) is our default; argmax differs near ties, so parity
        # runs set personalization_interp: logprobs
        self._interp_space = self.config.server_config.get(
            "personalization_interp", "probs")
        # the personal pass reads the CURRENT global params per round, so
        # round fusion would train local models against stale globals
        if int(self.config.server_config.get("rounds_per_step", 1) or 1) > 1:
            print_rank("personalization forces rounds_per_step=1")
            # item assignment, NOT setattr: rounds_per_step is an extras
            # key, and a plain attribute would be invisible to .get()
            self.config.server_config["rounds_per_step"] = 1

    def _round_housekeeping(self, round_no, val_freq, rec_freq,
                            skip_latest=False, rng_snapshot=None):
        super()._round_housekeeping(round_no, val_freq, rec_freq,
                                    skip_latest=skip_latest,
                                    rng_snapshot=rng_snapshot)
        # personalized eval: convex logit interpolation over users with
        # local state (reference convex_inference during run_testvalidate,
        # core/client.py:167-183)
        if round_no % val_freq == 0 and self.val_dataset is not None:
            self.personalized_accuracy(self.val_dataset)
        # persist ONLY the users updated this round (reference writes
        # <user>_model.tar per processed client, core/client.py:408-443);
        # fused mode has no host store — durability rides the model
        # checkpoint, whose strategy_state IS the personalization state
        if self.store is not None:
            self.store.save()

    # -- jitted per-user local pass ------------------------------------
    def _build_personal_fn(self):
        engine = self.engine
        client_update = engine.client_update
        cspec = P(CLIENTS_AXIS)
        rspec = P()
        from ..utils.compat import shard_map

        def shard_body(global_params, local_params, alphas, arrays,
                       sample_mask, client_mask, client_ids, client_lr, rng):
            def per_user(lp, alpha, arr, mask, cm, cid):
                rng_c = jax.random.fold_in(rng, cid + 104729)
                # global-model pass pseudo-grad (recomputed here so the
                # alpha update sees both pseudo-gradients, as in the
                # reference where both trainers run in the same round)
                pg_g, _, _, _ = client_update(global_params, arr, mask,
                                              client_lr, rng_c)
                # local-model pass
                pg_p, tl_p, ns, _ = client_update(lp, arr, mask, client_lr,
                                                  jax.random.fold_in(rng_c, 5))
                new_lp = jax.tree.map(lambda w, g: w - g, lp, pg_p)
                # alpha SGD step (utils/utils.py:607-617); the reference
                # calls alpha_update after BOTH trainings, so the dot uses
                # post-training params: (w_g - pg_g) - (lp - pg_p)
                dots = jax.tree.map(
                    lambda wg, wp, gg, gp: jnp.sum(
                        ((wg - gg) - (wp - gp)) *
                        (alpha * gg + (1.0 - alpha) * gp)),
                    global_params, lp, pg_g, pg_p)
                grad_alpha = sum(jax.tree.leaves(dots)) + 0.02 * alpha
                new_alpha = jnp.clip(alpha - client_lr * grad_alpha,
                                     1e-4, 0.9999)
                new_alpha = jnp.where(jnp.isfinite(new_alpha), new_alpha,
                                      jnp.asarray(self.alpha0))
                new_alpha = jnp.where(cm > 0, new_alpha, alpha)
                new_lp = jax.tree.map(
                    lambda new, old: jnp.where(cm > 0, new, old), new_lp, lp)
                return new_lp, new_alpha, tl_p * cm

            return jax.vmap(per_user)(local_params, alphas, arrays,
                                      sample_mask, client_mask, client_ids)

        fn = shard_map(
            shard_body, mesh=engine.mesh,
            in_specs=(rspec, cspec, cspec, cspec, cspec, cspec, cspec,
                      rspec, rspec),
            out_specs=cspec, check_vma=False)
        return jax.jit(fn, donate_argnums=(1,))

    # -- hook into the round loop --------------------------------------
    def train(self):
        state = super().train()
        if self.store is not None:
            self.store.save()
        return state

    def _sample(self):
        sampled = super()._sample()
        if self.store is not None:
            # host path only: fused_carry runs the personal pass inside
            # the round program (strategies/personalized.py), so sampling
            # degrades to the base sampler and the pipeline stays eligible
            self._run_personal_pass(sampled)
        return sampled

    def _stage_on_clients_axis(self, host_params_list, alphas, batch):
        """Stack per-user param pytrees + stage a packed round batch onto
        the clients mesh axis (shared by the round pass and the eval)."""
        sharding = NamedSharding(self.mesh, P(CLIENTS_AXIS))
        stage = lambda v: jax.device_put(v, sharding)
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *host_params_list)
        return (jax.tree.map(stage, stacked),
                stage(np.asarray(alphas, np.float32)),
                {k: stage(v) for k, v in batch.arrays.items()},
                stage(batch.sample_mask), stage(batch.client_mask), stage)

    def _run_personal_pass(self, sampled) -> None:
        """Train sampled users' local models + alphas for this round."""
        if self._personal_fn is None:
            self._personal_fn = self._build_personal_fn()
        batch = pack_round_batches(
            self.train_dataset, sampled, self.batch_size, self.max_steps,
            rng=self._np_rng, pad_clients_to=pad_to_mesh(len(sampled), self.mesh),
            desired_max_samples=self.desired_max_samples)
        k_pad = batch.client_mask.shape[0]
        if self._random_init:
            default = self._random_params()
        elif self._initial_params is not None:
            default = self._initial_params
        else:
            default = jax.device_get(self.state.params)
        locals_, alphas = [], []
        for j in range(k_pad):
            cid = int(batch.client_ids[j])
            lp, a = self.store.get(cid if cid >= 0 else -1, default)
            locals_.append(lp)
            alphas.append(a)
        lps_dev, alphas_dev, arrays_dev, smask, cmask, stage = \
            self._stage_on_clients_axis(locals_, alphas, batch)
        rng = self._next_rng()
        new_lp, new_alpha, tl = self._personal_fn(
            self.state.params, lps_dev, alphas_dev, arrays_dev, smask, cmask,
            stage(batch.client_ids),
            jnp.asarray(self.initial_lr_client * self.lr_weight, jnp.float32),
            rng)
        # one bundled fetch (two separate device_gets paid two transfers)
        new_lp, new_alpha = jax.device_get((new_lp, new_alpha))
        for j in range(k_pad):
            cid = int(batch.client_ids[j])
            if cid < 0:
                continue
            self.store.put(cid, jax.tree.map(lambda x: x[j], new_lp),
                           float(new_alpha[j]))

    def _random_params(self):
        sub = self._next_rng()
        return jax.device_get(self.task.init_params(sub))

    # -- personalized eval ---------------------------------------------
    def _build_personal_eval_fn(self):
        """One jitted shard_map+vmap program scoring ALL users' convex-
        interpolated logits (reference ``convex_inference``,
        ``utils/utils.py:600-605``) — users ride the clients mesh axis with
        their local params stacked, exactly like the round path."""
        task = self.task
        from ..utils.compat import shard_map
        cspec = P(CLIENTS_AXIS)
        rspec = P()

        logspace = self._interp_space == "logprobs"

        def shard_body(gp, lps, alphas, arrays, sample_mask, client_mask):
            def per_user(lp, alpha, arr, mask, cm):
                x = arr["x"].reshape((-1,) + arr["x"].shape[2:])
                y = arr["y"].reshape(-1).astype(jnp.int32)
                m = mask.reshape(-1) * cm
                squash = jax.nn.log_softmax if logspace else jax.nn.softmax
                probs = (alpha * squash(task.apply(lp, x)) +
                         (1.0 - alpha) * squash(task.apply(gp, x)))
                pred = jnp.argmax(probs, axis=-1)
                # per-user loss = (global CE + local CE) / 2, sample-
                # weighted across users — the reference's personalized
                # "Val loss" definition (core/client.py:218-219: plain
                # average of the two models' losses; alpha plays no role)
                flat = {"x": x, "y": y, "sample_mask": m}
                lg = task.loss(gp, flat, None, False)[0]
                ll = task.loss(lp, flat, None, False)[0]
                n = jnp.sum(m)
                return (jnp.sum((pred == y).astype(jnp.float32) * m),
                        jnp.sum(m),
                        0.5 * (lg + ll) * n * (cm > 0))

            c, t, ls = jax.vmap(per_user)(lps, alphas, arrays, sample_mask,
                                          client_mask)
            return (jax.lax.psum(jnp.sum(c), CLIENTS_AXIS),
                    jax.lax.psum(jnp.sum(t), CLIENTS_AXIS),
                    jax.lax.psum(jnp.sum(ls), CLIENTS_AXIS))

        fn = shard_map(shard_body, mesh=self.engine.mesh,
                       in_specs=(rspec, cspec, cspec, cspec, cspec, cspec),
                       out_specs=(rspec, rspec, rspec), check_vma=False)
        return jax.jit(fn)

    def personalized_accuracy(self, dataset) -> Optional[float]:
        """Back-compat wrapper: accuracy component of the personalized
        eval."""
        res = self.personalized_eval(dataset)
        return None if res is None else res[0]

    def personalized_eval(self, dataset) -> Optional[Tuple[float, float]]:
        """Convex-interpolated accuracy + reference-style personalized
        loss over ALL of the dataset's users — one compiled program
        services everyone.  Users without local state evaluate with the
        global model in both slots (interp of identical models == the
        global model; loss (g+g)/2 == g), exactly the reference's fallback
        when no ``<user>_model.tar`` exists (core/client.py:197-219).

        Chunk width is FIXED at the mesh's client-axis size: one local-model
        replica per device lane bounds the staging memory (K param copies is
        the real cost at ResNet scale), and the constant shape means exactly
        one compilation no matter how the store grows.  ``S`` respects the
        configured ``desired_max_samples`` cap when present."""
        if not hasattr(self.task, "apply"):
            return None
        if self.store is None and \
                getattr(self, "fleet_pager", None) is not None:
            # fleet paged carry: the device tables hold only the page
            # pool's resident slots, but eval boundaries fully drain
            # the pipeline ring, so the pager's HOST store holds every
            # participated user's current (local, alpha, seen) row —
            # zero device reads here at all
            pager = self.fleet_pager
            if not pager.has_rows():
                return None  # nothing personalized yet
            gp_host = jax.device_get(self.state.params)
            leaves, treedef = jax.tree.flatten(gp_host)
            spans = []
            off = 0
            for leaf in leaves:
                spans.append((off, int(np.prod(leaf.shape)), leaf.shape))
                off += spans[-1][1]

            def _unravel_np(vec):
                return jax.tree.unflatten(treedef, [
                    np.asarray(vec[o:o + n]).reshape(shp)
                    for o, n, shp in spans])

            def get_lp(u):
                row = pager.user_row(u)
                return (_unravel_np(row["local"])
                        if row is not None and float(row["seen"]) > 0
                        else gp_host)

            def get_alpha(u):
                row = pager.user_row(u)
                return (float(row["alpha"])
                        if row is not None and float(row["seen"]) > 0
                        else self.alpha0)
        elif self.store is None:
            # fused_carry: ONE explicit fetch of the carry tables at this
            # eval boundary (the sanctioned crossing — eval boundaries
            # already fetch; the per-round loop still pays exactly one
            # packed transfer).  Rows are unraveled host-side in
            # tree-flatten order, the exact inverse of the strategy's
            # ravel_pytree rows — no device round trip per user.  The
            # cheap ``seen`` gate crosses FIRST: when nothing is
            # personalized yet the early return must not have paid for
            # the [N, n_params] local table (or the model params).
            ss = self.state.strategy_state
            # flint: disable=host-sync deliberate split — the [N] seen gate crosses alone so the early return never pays for the [N, n_params] local table
            seen_tab = np.asarray(jax.device_get(ss["seen"]))
            if not bool(np.any(seen_tab > 0)):
                # nothing personalized yet (e.g. initial_val before
                # round 1) — the standard global eval covers this state
                return None
            gp_host = jax.device_get(self.state.params)
            local_tab, alpha_tab = jax.device_get(
                (ss["local"], ss["alpha"]))
            leaves, treedef = jax.tree.flatten(gp_host)
            spans = []
            off = 0
            for leaf in leaves:
                spans.append((off, int(np.prod(leaf.shape)), leaf.shape))
                off += spans[-1][1]

            def _unravel_np(vec):
                return jax.tree.unflatten(treedef, [
                    np.asarray(vec[o:o + n]).reshape(shp)
                    for o, n, shp in spans])

            def get_lp(u):
                return (_unravel_np(local_tab[u]) if u < len(seen_tab)
                        and seen_tab[u] > 0 else gp_host)

            def get_alpha(u):
                return (float(alpha_tab[u]) if u < len(seen_tab)
                        and seen_tab[u] > 0 else self.alpha0)
        else:
            if not self.store.alpha:
                # nothing personalized yet (e.g. initial_val before
                # round 1): the whole program would reduce to 4 redundant
                # global forwards per user — skip; the standard global
                # eval already covers this state
                return None
            gp_host = jax.device_get(self.state.params)
            get_lp = lambda u: self.store.params.get(u, gp_host)
            get_alpha = lambda u: self.store.alpha.get(u, self.alpha0)
        uids = list(range(len(dataset)))
        if not uids:
            return None
        if self._personal_eval_fn is None:
            self._personal_eval_fn = self._build_personal_eval_fn()
        from ..data.batching import steps_for
        bs = int(self.config.server_config.data_config.val.get(
            "batch_size", self.batch_size))
        S = steps_for(int(max(dataset.num_samples)), bs,
                      self.desired_max_samples)
        chunk_k = self.mesh.shape[CLIENTS_AXIS]
        correct = total = loss_sum = 0.0
        for i in range(0, len(uids), chunk_k):
            part = uids[i:i + chunk_k]
            batch = pack_round_batches(
                dataset, part, bs, S, shuffle=False, pad_clients_to=chunk_k,
                desired_max_samples=self.desired_max_samples)
            lps = [get_lp(u) for u in part]
            alphas = [get_alpha(u) for u in part]
            while len(lps) < chunk_k:  # mesh-padding lanes (client_mask 0)
                lps.append(gp_host)
                alphas.append(self.alpha0)
            lps_dev, alphas_dev, arrays_dev, smask, cmask, _ = \
                self._stage_on_clients_axis(lps, alphas, batch)
            c, t, ls = self._personal_eval_fn(
                self.state.params, lps_dev, alphas_dev, arrays_dev,
                smask, cmask)
            correct += float(c)
            total += float(t)
            loss_sum += float(ls)
        if total == 0:
            return None
        acc = correct / total
        loss = loss_sum / total
        log_metric("Personalized val acc", acc, step=self.state.round)
        log_metric("Personalized val loss", loss, step=self.state.round)
        return acc, loss
