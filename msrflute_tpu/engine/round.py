"""The federated round as one jitted SPMD program.

Parity target: the whole middle of the reference stack —
``federated.Server.dispatch_clients/process_clients``
(``core/federated.py:281-424``), the Worker recv loop
(``core/federated.py:482-632``), and the server-side aggregation half of
``OptimizationServer.train`` (``core/server.py:337-427``).

TPU-native redesign (SURVEY.md §5.8): no message protocol, no work queue.
One compiled ``round_step``:

    shard_map over mesh 'clients' axis:
        vmap(client_update) over the shard's clients        # local SGD
        per-client strategy weight + payload transform      # DP/quant/freeze
        weighted local sums -> psum over 'clients'          # "collection"
    strategy.combine (+ staleness buffer, global DP)        # aggregation
    server optax step on the aggregate pseudo-gradient      # ModelUpdater

The per-round model "broadcast" (reference ``core/federated.py:330-335``,
K-1 unicasts) is just the replicated ``params`` operand — XLA keeps it
resident on every chip; the "harvest" poll loop (``core/federated.py:216-229``)
is a single ``psum`` riding ICI.  Greedy work-stealing is replaced by static
client sharding; imbalance is absorbed by masked padding, which costs FLOPs
on padded samples instead of latency on stragglers — the right trade on MXUs.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..utils.compat import shard_map

from ..config import FLUTEConfig
from ..data.batching import RoundBatch
from ..models.base import BaseTask
from ..optim import make_optimizer
from ..parallel.mesh import CLIENTS_AXIS, MODEL_AXIS, make_mesh
from ..resilience.chaos import (CORRUPT_NAN, CORRUPT_SCALE,
                                CORRUPT_SIGN_FLIP)
from ..traffic.schedule import STALE_HIST_BINS
from ..robust import make_shield
from ..strategies.base import BaseStrategy
from ..telemetry import devbus_config_enabled, xla_config_enabled
from ..telemetry import xla as xla_telemetry
from ..telemetry.devbus import DeviceMetricBus
from ..utils.flatpack import AxisPacker, FlatPacker, ScalarStager
from .client_update import (ClientHParams, build_client_update,
                            build_mega_update, _clip_by_global_norm)


@dataclass
class PackedStats:
    """Lazy handle to one chunk's round stats, packed on device.

    The round program returns its ~dozen per-round scalars / per-client
    vectors as ONE 1-D buffer per distinct dtype (``utils/flatpack.py``),
    so the host pays one ``device_get`` per dtype group per chunk instead
    of one per stat (the per-buffer dispatch overhead measured by
    ``tools/dispatch_cost_probe.py``).  Nothing is fetched until
    :meth:`fetch` — the server's pipelined loop holds this handle while
    the device executes the next chunk and drains it afterwards.
    """

    vecs: Dict[str, jax.Array]  #: {dtype_str: 1-D (or [R, n]) device buffer}
    packer: FlatPacker          #: single-round slot table
    rounds: int                 #: R rounds in this chunk
    stacked: bool               #: True if ``vecs`` carry a leading [R] axis

    def fetch(self) -> Dict[str, np.ndarray]:
        """Fetch + decode: ONE host transfer per dtype group (the honest
        end-of-chunk fence), then pure numpy views.  Leaves come back
        with a leading ``[R]`` round axis like ``run_rounds`` always
        returned."""
        host = jax.device_get(self.vecs)
        if self.stacked:
            return self.packer.unpack_np_stacked(host)
        tree = self.packer.unpack_np(host)
        return {k: np.asarray(v)[None] for k, v in tree.items()}


@dataclass
class BucketedStats:
    """Lazy handle to a bucketed chunk's per-round packed stats.

    Cohort bucketing dispatches each round as N collect programs plus a
    finalize whose packed stats ride the same one-buffer-per-dtype
    contract as :class:`PackedStats` — but rounds of one chunk may have
    different cohort-vector lengths (per-client privacy stats are laid
    out as the concatenation of that round's buckets), so the chunk's
    stats cannot ride one stacked buffer.  ``fetch`` pulls every round's
    buffers in ONE ``device_get`` call (still one packed buffer per
    dtype group per round — the invariant), then stacks host-side:
    scalars to ``[R]``, per-client vectors zero-padded to the chunk max
    (their mask is the batches' client_mask, padded identically by the
    server)."""

    rounds_stats: list  #: one PackedStats per round, dispatch order

    @property
    def rounds(self) -> int:
        return len(self.rounds_stats)

    def fetch(self) -> Dict[str, np.ndarray]:
        host = jax.device_get([ps.vecs for ps in self.rounds_stats])
        decoded = [ps.packer.unpack_np(h)
                   for ps, h in zip(self.rounds_stats, host)]
        out: Dict[str, np.ndarray] = {}
        for key in decoded[0]:
            vals = [np.asarray(d[key]) for d in decoded]
            if vals[0].ndim == 0:
                out[key] = np.asarray(vals)
                continue
            width = max(v.shape[0] for v in vals)
            out[key] = np.stack([
                v if v.shape[0] == width else np.concatenate(
                    [v, np.zeros((width - v.shape[0],) + v.shape[1:],
                                 v.dtype)])
                for v in vals])
        return out


@dataclass
class ServerState:
    """Replicated server-side state threaded through rounds
    (the analogue of the reference's global model + ModelUpdater optimizer +
    strategy buffers)."""

    params: Any
    opt_state: Any
    strategy_state: Any
    round: int = 0


class RoundEngine:
    """Compiles and runs the per-round SPMD program."""

    def __init__(self, task: BaseTask, config: FLUTEConfig,
                 strategy: BaseStrategy, mesh: Optional[Mesh] = None):
        self.task = task
        self.config = config
        self.strategy = strategy
        strategy.task = task  # strategies may need model apply()/loss()
        self.mesh = mesh if mesh is not None else make_mesh()

        cc = config.client_config
        sc = config.server_config
        freeze = cc.get("freeze_layer") or []
        if isinstance(freeze, str):
            freeze = [freeze]
        # megakernel local SGD (server_config.megakernel): epoch/step
        # fusion is DEFAULT-ON (one scan over the flattened
        # [num_epochs * steps] grid; num_epochs == 1 traces the exact
        # historical program), the pallas fused SGD apply opt-in.  An
        # explicit `enable: false` restores the full legacy trace.
        _mk_raw = sc.get("megakernel") or {}
        _mk_on = not _mk_raw or bool(_mk_raw.get("enable", True))
        self.megakernel = {
            "fused_epochs": bool(_mk_raw.get("fused_epochs", True))
            if _mk_on else False,
            "pallas_apply": bool(_mk_raw.get("pallas_apply", False))
            if _mk_on else False,
        }
        if self.megakernel["pallas_apply"] and \
                jax.default_backend() != "tpu":
            # the round runs client_update inside shard_map over virtual
            # CPU devices off-TPU, where interpret-mode pallas kernels
            # deadlock (the documented reason ops/pallas_attention.py
            # defaults to dense there) — refuse loudly instead of
            # hanging the first round
            raise ValueError(
                "megakernel.pallas_apply requires a TPU backend: the "
                "interpret-mode kernel cannot run inside the shard_map'd "
                "round on CPU — drop the flag (fused_epochs still "
                "applies) or run on TPU")
        # precision policy (server_config.precision): params/compute/
        # stats dtypes for the client inner loop.  Absent — or every
        # entry "float32" — compiles the exact f32 legacy trace (the
        # bit-identity default); `compute: bfloat16` runs the forward/
        # backward in bf16 while master params and packed-stats
        # accumulators stay f32.
        _prec_raw = sc.get("precision") or {}
        _prec_on = bool(_prec_raw) and bool(_prec_raw.get("enable", True))
        self.precision = ({k: str(_prec_raw[k])
                           for k in ("params", "compute", "stats")
                           if _prec_raw.get(k) is not None}
                          if _prec_on else {})
        self.hparams = ClientHParams(
            max_grad_norm=cc.get("max_grad_norm"),
            fedprox_mu=float(cc.get("fedprox_mu", 0.0) or 0.0),
            num_epochs=int(cc.get("num_epochs", 1) or 1),
            freeze_layers=tuple(freeze),
            fused_epochs=self.megakernel["fused_epochs"],
            pallas_apply=self.megakernel["pallas_apply"],
            param_dtype=self.precision.get("params"),
            compute_dtype=self.precision.get("compute"),
            stats_dtype=self.precision.get("stats"),
        )
        self.client_update = build_client_update(
            task, cc.optimizer_config, self.hparams)
        self.server_tx = make_optimizer(sc.optimizer_config)
        self.server_max_grad_norm = sc.get("max_grad_norm")
        self.stale_prob = float(getattr(strategy, "stale_prob", 0.0) or 0.0)
        if self.stale_prob > 0.0 and not strategy.supports_staleness:
            raise ValueError(
                f"{type(strategy).__name__} does not support stale_prob > 0")
        if sc.get("wantRL", False) and not strategy.supports_rl:
            raise ValueError(
                f"{type(strategy).__name__} does not support wantRL")
        if getattr(strategy, "owns_server_update", False):
            opt_type = str(sc.optimizer_config.get("type", "sgd")).lower()
            if opt_type != "sgd":
                raise ValueError(
                    f"{type(strategy).__name__} applies its own coupled "
                    f"server update; server optimizer_config type="
                    f"{opt_type!r} would be silently ignored — use sgd "
                    "(the lr still scales the update)")
        self.dump_norm_stats = bool(config.get("dump_norm_stats",
                                               sc.get("dump_norm_stats",
                                                      False)))
        # scan-over-client-chunks: bound HBM at large K.  vmap over all K
        # clients materializes K x (activations + payload tree) at once —
        # measured OOM at K=1024 on a 16G v5e (bench_scale.json); chunking
        # scans vmap(chunk) accumulating the weighted sums, so memory is
        # O(chunk) while the psum'd result is identical up to f32
        # reassociation (tests/test_client_chunking.py).
        cpc = sc.get("clients_per_chunk")
        self.clients_per_chunk = int(cpc) if cpc else None
        if self.clients_per_chunk and self.dump_norm_stats:
            raise ValueError(
                "clients_per_chunk is incompatible with dump_norm_stats: "
                "per-client cosines need every payload against the final "
                "aggregate, which chunked accumulation never materializes — "
                "disable one of them")

        # device-resident carry state (universal overlap): the strategy
        # keeps its cross-round per-client tables (SCAFFOLD controls, EF
        # residuals, personalization heads/alphas) INSIDE strategy_state,
        # gathers its rows per client in-program and scatters the update
        # back via apply_carry — the round-k -> k+1 data dependency lives
        # on device, so these strategies pipeline like FedAvg.  The
        # server flips the flag (enable_device_carry) before building the
        # engine when server_config.fused_carry is set.
        self.device_carry = bool(getattr(strategy, "device_carry", False))
        if self.device_carry and self.clients_per_chunk:
            raise ValueError(
                "fused_carry is incompatible with clients_per_chunk: the "
                "carry scatter needs every client's update row, which "
                "chunked accumulation never materializes — disable one")
        # fleet paged carry (server_config.fleet + fused_carry): the
        # carry tables are a fixed-capacity page pool (engine/paging.py)
        # and the round program takes ONE extra per-round data operand —
        # carry_slots [K] int32, the host-remapped pool slot per lane —
        # which the carry gather/scatter indexes INSTEAD of client_ids.
        # Per-client rng streams keep folding on the true client id, so
        # per-client math is bit-identical to resident tables.  Static
        # at engine build: without the fleet block the program is byte-
        # for-byte the PR 6 trace (carry_slots IS client_ids in-trace).
        _fleet_raw = sc.get("fleet") or {}
        self.carry_paged = bool(
            self.device_carry and _fleet_raw and
            _fleet_raw.get("enable", True))
        # mesh-sharded page pool: the tables' slot axis splits over
        # CLIENTS_AXIS into contiguous per-shard blocks (the same split
        # shard_map applies to the cohort grids), so the in-program
        # carry gather/scatter is shard-local — the engine converts the
        # GLOBAL carry_slots operand to shard-local indices inside the
        # shard_map body using this block width.
        self._carry_shard_slots = 0
        if self.carry_paged:
            rows = int(getattr(strategy, "carry_rows", 0) or 0)
            shards = int(self.mesh.shape[CLIENTS_AXIS])
            if rows <= 0 or rows % shards:
                raise ValueError(
                    f"fleet paged carry: page pool of {rows} slots does "
                    f"not split over the {shards}-shard clients mesh "
                    "axis — the server quantizes page_pool_slots to a "
                    "mesh multiple before building the engine")
            self._carry_shard_slots = rows // shards

        # fused RL (server_config.wantRL + fused_carry): the DQN
        # aggregation-weight tuner lives in strategy_state (rl/fused.py)
        # and re-weights the gathered payload stack in-program; the
        # reward is the round-over-round train-loss delta (delayed one
        # round) instead of the host path's val-accuracy comparison —
        # the documented tradeoff that buys full overlap.
        self.rl_fused = bool(sc.get("wantRL", False) and
                             sc.get("fused_carry", False))
        self._rl = None
        if self.rl_fused:
            if not strategy.supports_rl:
                raise ValueError(
                    f"{type(strategy).__name__} does not support wantRL")
            if self.device_carry:
                raise ValueError(
                    "fused RL does not compose with a device-carry "
                    "strategy (scaffold/ef_quant/personalization): the "
                    "RL re-weighting assumes the plain single-payload "
                    "flow — drop wantRL or use fedavg/dga")
            if strategy.stateful or \
                    getattr(strategy, "adaptive_clip", None) is not None:
                raise ValueError(
                    "fused RL requires a stateless strategy combine "
                    "(no adaptive_clipping / strategy state): the RL "
                    "weights replace the combine entirely")
            if getattr(strategy, "wants_cohort", False) or \
                    strategy.unit_weight_parts:
                raise ValueError(
                    "fused RL does not compose with masked multi-part "
                    "payloads (secure_agg/fedlabels): re-weighting would "
                    "break mask cancellation")
            if self.clients_per_chunk:
                raise ValueError(
                    "fused RL is incompatible with clients_per_chunk: "
                    "re-weighting needs the full payload stack")
            if float(getattr(strategy, "stale_prob", 0.0) or 0.0) > 0.0:
                raise ValueError("fused RL does not support stale_prob")
            from ..config import RLConfig
            from ..rl.fused import FusedRL
            rl_cfg = sc.RL if getattr(sc, "RL", None) is not None \
                else RLConfig.from_dict({})
            if bool(rl_cfg.get("wantLSTM", False)):
                raise ValueError(
                    "fused RL does not support wantLSTM — the state-"
                    "window recurrence is host-side; drop fused_carry "
                    "for LSTM RL runs")
            ncpi = sc.get("num_clients_per_iteration", 10)
            if not isinstance(ncpi, int):
                raise ValueError(
                    "wantRL requires a fixed num_clients_per_iteration")
            from ..parallel.mesh import pad_to_mesh
            self._rl = FusedRL(rl_cfg, pad_to_mesh(int(ncpi), self.mesh))

        # single-buffer input staging (server_config.input_staging,
        # default on): per-round host inputs — masks, ids, chaos
        # vectors, lr/round scalars, and the feature (or index) grids —
        # cross the host boundary as ONE buffer per dtype group
        # (utils/flatpack.py AxisPacker/ScalarStager) instead of ~8-10
        # per-leaf device_puts per dispatch (tools/dispatch_cost_probe).
        self.input_staging = bool(sc.get("input_staging", True))
        self._staged_cache: Dict[Any, Callable] = {}
        #: dispatch-cost observability (bench extras + the tier-1
        #: regression guard): host->device put calls and bytes of the
        #: most recent dispatch
        self.last_dispatch_puts = 0
        self.last_staged_bytes = 0

        # deterministic chaos client faults (server_config.chaos): when the
        # schedule injects dropout/straggling, the round program takes two
        # extra per-round data operands — drop [K] and keep_steps [K] —
        # and folds them into client_mask / sample_mask IN-program, so the
        # faults cost no recompile and the injected-fault counters ride
        # the packed-stats single-transfer path (resilience/chaos.py).
        # Static at engine build: a chaos-free config compiles the exact
        # program it always did.  Read straight from the config block —
        # the ONE live ChaosSchedule (counters, IO-fault stream) belongs
        # to the server; a second instance here would silently diverge.
        _chaos_raw = sc.get("chaos") or {}
        _chaos_on = bool(_chaos_raw and _chaos_raw.get("enable", True))
        self.chaos_client_faults = bool(
            _chaos_on and
            (float(_chaos_raw.get("dropout_rate", 0.0) or 0.0) > 0.0 or
             float(_chaos_raw.get("straggler_rate", 0.0) or 0.0) > 0.0))
        # adversarial corruption streams (fluteshield's attack half):
        # when any corrupt_* rate is non-zero the program takes ONE more
        # per-round data operand — mode [K] int32 — and applies the
        # NaN/scale/sign-flip transform to the default payload inside
        # the vmap'd client body.  Same static-at-build discipline as
        # the fault flag above: zero rates compile the exact program a
        # corruption-free config always had.
        self.chaos_corruption = bool(
            _chaos_on and
            any(float(_chaos_raw.get(k, 0.0) or 0.0) > 0.0
                for k in ("corrupt_nan_rate", "corrupt_scale_rate",
                          "corrupt_sign_flip_rate")))
        self._corrupt_scale = float(
            _chaos_raw.get("corrupt_scale_factor", 10.0) or 10.0)
        self._corrupt_flip_scale = float(
            _chaos_raw.get("corrupt_sign_flip_scale", 1.0) or 1.0)

        # fluteflow traced staleness (server_config.traffic, buffered
        # mode, with a strategy that declares supports_traced_staleness
        # — FedBuff): the round program takes ONE more per-round data
        # operand — staleness [K] int32, the TRUE broadcast-version gap
        # the arrival plane measured — threaded on the exact rails the
        # chaos vectors ride (appended after corrupt_mode in every
        # positional order), so traffic costs no recompile and the
        # per-staleness histogram counters ride the packed-stats single
        # transfer.  Static at engine build: a traffic-free config (or
        # sync mode, or a staleness-blind strategy) compiles the exact
        # program it always did.
        _traffic_raw = sc.get("traffic") or {}
        _traffic_on = bool(_traffic_raw and
                           _traffic_raw.get("enable", True))
        self.traffic_staleness = bool(
            _traffic_on and
            str(_traffic_raw.get("mode", "buffered")) == "buffered" and
            getattr(strategy, "supports_traced_staleness", False))
        if self.traffic_staleness and self.clients_per_chunk:
            raise ValueError(
                "server_config.traffic traced staleness cannot compose "
                "with clients_per_chunk: the chunk scan's operand tuple "
                "is fixed per chunk — disable one of them")

        # fluteshield screened aggregation (server_config.robust): the
        # quarantine mask is computed INSIDE the round program from the
        # per-client payloads (robust/shield.py) and folds into
        # client_mask/weights as data — no recompile, counters ride the
        # packed-stats single transfer.  None (no block / enable: false)
        # is the firewall path: the exact pre-fluteshield program.
        self.shield = make_shield(sc)
        if self.shield is not None:
            from ..strategies.fedavg import FedAvg
            from ..strategies.robust import RobustFedAvg
            from ..strategies.secure_agg import SecureAgg
            # exact-class check: QFFL/FedBuff/... subclass FedAvg but
            # combine through their own payload parts, which quarantine
            # zeroing would silently corrupt — isinstance would admit
            # them.  SecureAgg is admitted by name: its masked path
            # screens on submitted norms (Shield.screen_masked) and a
            # quarantined client feeds the pairwise-mask cancellation as
            # one more dropout cause (tests/test_secagg_compose.py)
            if type(strategy) not in (FedAvg, RobustFedAvg, SecureAgg):
                raise ValueError(
                    "server_config.robust requires strategy: fedavg/"
                    f"fedprox/secure_agg — {type(strategy).__name__} "
                    "aggregates through its own payload parts and would "
                    "bypass the screening")
            if isinstance(strategy, SecureAgg) and self.shield.wants_stack:
                raise ValueError(
                    f"robust.aggregator={self.shield.aggregator!r} sorts "
                    "per-client payload coordinates, but secure_agg "
                    "submissions are masked int32 group elements — only "
                    "the SUM is meaningful.  Use aggregator: mean (norm "
                    "screening still applies, on submitted norms)")
            if self.clients_per_chunk:
                raise ValueError(
                    "server_config.robust is incompatible with "
                    "clients_per_chunk: median-of-norms screening (and "
                    "the trimmed-mean/median payload stack) needs every "
                    "client's payload against the full cohort, which "
                    "chunked accumulation never materializes — disable "
                    "one of them")
            if getattr(strategy, "adaptive_clip", None) is not None:
                # screening zeroes only the default payload part; the
                # adaptive-clip quantile aggregates per-client below-clip
                # votes that quarantine cannot retract, so the clip would
                # drift off the population actually being aggregated
                raise ValueError(
                    "server_config.robust is incompatible with "
                    "dp_config.adaptive_clipping: quarantined clients' "
                    "below-clip votes would still steer the clip "
                    "quantile — use a fixed max_grad or drop the robust "
                    "block")
            if self.shield.wants_stack and \
                    not getattr(strategy, "wants_client_stack", False):
                raise ValueError(
                    f"robust.aggregator={self.shield.aggregator!r} needs "
                    "the stack-combining RobustFedAvg strategy "
                    "(strategies/robust.py); the server wires this — "
                    "constructing RoundEngine directly, pass it yourself")

        # cohort shape-bucketing (server_config.cohort_bucketing): the
        # round's sampled clients partition into a small set of
        # power-of-two step buckets; each bucket dispatches a COMPACT
        # [K_b, S_b, B, ...] collect program (the same per-client math
        # as the fused round — masked padding steps are no-op-pinned,
        # so per-client updates are bit-identical), and a finalize
        # program combines the per-bucket partials into the weighted
        # aggregate ON DEVICE in deterministic bucket order.  One packed
        # stats fetch per round and zero implicit host syncs, unchanged.
        _cb_raw = sc.get("cohort_bucketing") or {}
        self.cohort_bucketing = bool(_cb_raw and _cb_raw.get("enable", True))
        # an EXPLICIT max_buckets: 0 must reach the < 1 refusal below,
        # not silently coerce to the default (bench injects blocks past
        # schema validation)
        _mb = _cb_raw.get("max_buckets")
        self.bucket_max = 4 if _mb is None else int(_mb)
        if self.cohort_bucketing:
            if self.bucket_max < 1:
                raise ValueError("cohort_bucketing.max_buckets must be >= 1")
            if self.clients_per_chunk:
                raise ValueError(
                    "cohort_bucketing is incompatible with "
                    "clients_per_chunk: the chunk scan assumes one grid "
                    "shape per round — pick one HBM/FLOP bounding scheme")
            if self.dump_norm_stats:
                raise ValueError(
                    "cohort_bucketing is incompatible with "
                    "dump_norm_stats: per-client cosines need every "
                    "payload against the final aggregate inside ONE "
                    "program — disable one of them")
            if self.rl_fused:
                raise ValueError(
                    "cohort_bucketing does not compose with fused RL: "
                    "the DQN re-weighting assumes the single-grid payload "
                    "stack — drop wantRL or cohort_bucketing")
            # NOTE: wants_cohort strategies (secure_agg) now compose —
            # each bucket runs its own pairwise-mask graph over the
            # bucket's sampled sub-cohort and the finalize cancels
            # residual masks per bucket before decoding; the int32
            # telescoping is exact either way, so bucketed == monolithic
            # bit-identical (tests/test_secagg_compose.py)
            if not self.input_staging:
                raise ValueError(
                    "cohort_bucketing requires input_staging (the "
                    "legacy per-leaf dispatch path is kept only for the "
                    "staging A/B) — drop `input_staging: false`")
            if self.shield is not None and \
                    float(getattr(strategy, "stale_prob", 0.0) or 0.0) > 0:
                raise ValueError(
                    "cohort_bucketing + robust screening does not "
                    "support stale_prob > 0")
        # cross-client megabatching (server_config.megabatch): within a
        # step bucket, many SMALL clients' step sequences concatenate
        # into super-batch LANES read off a [lanes, depth] pointer tape
        # (data/batching.plan_megabatch), and the collect program runs
        # the segment-carrying lane scan (client_update.
        # build_mega_update) instead of one vmap lane per client — same
        # per-client math, folded on true client ids, with a cheap
        # fake-update vmap pass replaying the strategy's weight/
        # transform/carry logic on the harvested rows.  The dispatch
        # gate prices megabatch vs per-client vmap PER BUCKET (like the
        # attention flash/dense gate) and falls back loudly via the
        # buffered ``megabatch_fallback`` event.
        _mgb_raw = sc.get("megabatch") or {}
        self.megabatch = bool(_mgb_raw and _mgb_raw.get("enable", True))
        self.megabatch_min_gain = float(
            _mgb_raw.get("min_gain", 0.1) or 0.0)
        self.megabatch_autotune = bool(_mgb_raw.get("autotune", True))
        self.mega_update = None
        if self.megabatch:
            if not self.cohort_bucketing:
                raise ValueError(
                    "megabatch requires cohort_bucketing: the super-"
                    "batch tape repacks the per-bucket step grids — add "
                    "the cohort_bucketing block or drop megabatch")
            _pm = getattr(config, "privacy_metrics_config", None)
            if _pm is not None and _pm.get("apply_metrics", False):
                raise ValueError(
                    "megabatch is incompatible with privacy_metrics_"
                    "config.apply_metrics: the attack metrics replay "
                    "each client's own batches against its payload, "
                    "which the fused lane scan no longer materializes "
                    "per client — disable one of them")
            if not getattr(strategy, "supports_megabatch", True):
                raise ValueError(
                    f"megabatch does not compose with "
                    f"{type(strategy).__name__}: its training loop "
                    "steps outside the client_update contract the lane "
                    "scan reproduces (fedlabels' dual sup/unsup "
                    "passes) — drop megabatch")
            if self.hparams.pallas_apply:
                raise ValueError(
                    "megabatch is incompatible with megakernel."
                    "pallas_apply: the flat fused kernel has no "
                    "segment-reset lane — drop one of them")
            self.mega_update = build_mega_update(
                task, cc.optimizer_config, self.hparams)
        #: per-(K_b, S_b) dispatch-gate verdicts ("mega"/"vmap") — the
        #: server reports the chosen arm per bucket on the scorecard
        self._mega_gate: Dict[Any, str] = {}
        #: buffered megabatch_fallback event records (the attention
        #: gate's _PENDING_EVENTS discipline), drained by the server
        self._mega_events: list = []

        #: staged per-bucket collect programs, keyed by grid geometry +
        #: packer signatures — one compiled variant per distinct
        #: (K_b, S_b) shape, which the recompile sentinel watches
        self._bucket_collect_cache: Dict[Any, Callable] = {}
        self._bucket_collect_core: Dict[bool, Callable] = {}
        self._bucket_finalize = None
        #: distinct (K_b, S_b) collect grids this run compiled — the
        #: scorecard/bench closure metric gated against max_buckets
        self.bucket_shapes_seen: set = set()

        # flutescope device-metric bus (server_config.telemetry.devbus):
        # engine/strategy code publishes per-round device scalars at
        # TRACE time; round_step drains them into round_stats just
        # before the flatpack pack, so every published value rides the
        # existing single per-dtype-group transfer — zero new
        # device_gets.  Static at engine build like the chaos flag: a
        # telemetry-free config compiles the exact program it always
        # did.  Strategies publish through their `devbus` attribute.
        self.devbus = DeviceMetricBus(
            devbus_config_enabled(sc.get("telemetry")))
        strategy.devbus = self.devbus

        # flutescope device-truth (server_config.telemetry.xla): wrap
        # each jitted entry point in an AOT-cached _InstrumentedFn so
        # every compile is observed with its cost/memory analysis and
        # the recompile sentinel sees signature churn (telemetry/
        # xla.py).  None when telemetry/xla is off — the zero-cost
        # contract: no introspection objects, the plain jit callables,
        # identical dispatch path.
        self.xla = (xla_telemetry.XlaIntrospector()
                    if xla_config_enabled(sc.get("telemetry")) else None)
        #: entry-point names in compile order — ALWAYS on (a list append
        #: per compiled program variant, read from the jit caches; no
        #: introspection objects).  `recompile_count` derives from it,
        #: so bench.py can report recompiles without telemetry enabled.
        self.compile_log: list = []
        self._compile_seen: Dict[Any, int] = {}

        self._client_sharding = NamedSharding(self.mesh, P(CLIENTS_AXIS))
        self._replicated = NamedSharding(self.mesh, P())
        #: device-resident sample pool (build_sample_pool); when set, round
        #: inputs are [K,S,B] indices and the gather runs in-program
        self._pool = None
        # partition mode: explicit shard_map collectives (default), or
        # GSPMD sharding propagation (required for a model axis > 1)
        mesh_cfg = config.mesh_config or {}
        default_mode = ("gspmd" if self.mesh.shape.get(MODEL_AXIS, 1) > 1
                        else "shard_map")
        self.partition_mode = mesh_cfg.get("partition", default_mode)
        self._multi_cache = {}
        #: {geometry key: FlatPacker} — slot tables for decoding the
        #: packed stats buffers, recorded when the round program traces
        self._stats_packers: Dict[Any, FlatPacker] = {}
        self._round_step = self._build_round_step()

    # ------------------------------------------------------------------
    def _instrument(self, name: str, jitted: Callable,
                    rounds: int = 1) -> Callable:
        """Route one jitted entry point through the device-truth layer
        (cost/memory capture + recompile sentinel) when it is on; the
        plain jit callable otherwise."""
        if self.xla is None:
            return jitted
        return self.xla.wrap(name, jitted, rounds=rounds)

    @staticmethod
    def _roofline_secs(cost) -> float:
        """Roofline score of one compiled arm (``max(flops/peak,
        bytes/bw)``) — the same one-number verdict the attention
        flash/dense gate compares (ops/pallas_attention.py)."""
        from ..ops.pallas_attention import _roofline_secs
        return _roofline_secs(cost)

    def push_megabatch_event(self, rec: Dict[str, Any]) -> None:
        """Buffer one ``megabatch_fallback`` dispatch-gate record
        (mirrors the attention gate's pending-events discipline; capped
        so an undrained session cannot grow it unboundedly).  The
        server's host tail drains + emits them into the structured-event
        stream (docs/observability.md)."""
        if len(self._mega_events) < 64:
            self._mega_events.append(dict(rec))

    def drain_megabatch_events(self) -> list:
        """Hand the buffered megabatch gate events to the caller (the
        server's host tail, which owns emitting them)."""
        out, self._mega_events = self._mega_events, []
        return out

    def _note_compiles(self, name: str, fn: Callable) -> None:
        """Append one ``compile_log`` entry per NEW compiled variant of
        ``fn`` since the last note — read from the wrapper's AOT cache
        or the pjit dispatch cache, so the count is the truth of what
        XLA compiled, not a guess from our own cache keys."""
        if hasattr(fn, "cache_len"):          # _InstrumentedFn
            n = int(fn.cache_len)
        elif hasattr(fn, "_cache_size"):      # pjit function
            try:
                n = int(fn._cache_size())
            except Exception:
                return
        else:
            return
        key = (name, id(fn))
        prev = self._compile_seen.get(key, 0)
        for _ in range(n - prev):
            self.compile_log.append(name)
        self._compile_seen[key] = max(prev, n)

    @property
    def recompile_count(self) -> int:
        """Compiled program variants beyond the first per entry point —
        the always-on recompile counter (the sentinel's event stream,
        with operand diffs, additionally exists when telemetry/xla is
        on)."""
        return len(self.compile_log) - len(set(self.compile_log))

    # ------------------------------------------------------------------
    def init_state(self, rng: jax.Array, params: Any = None) -> ServerState:
        if params is None:
            params = self.task.init_params(rng)
        if self.partition_mode == "gspmd" and \
                self.mesh.shape.get(MODEL_AXIS, 1) > 1:
            from ..parallel.sharding import infer_model_sharding
            shardings = infer_model_sharding(params, self.mesh)
            params = jax.tree.map(jax.device_put, params, shardings)
            opt_state = jax.jit(self.server_tx.init)(params)
        else:
            params = jax.device_put(params, self._replicated)
            opt_state = jax.jit(self.server_tx.init,
                                out_shardings=self._replicated)(params)
        strategy_state = self.strategy.init_state(params)
        if self.carry_paged:
            strategy_state = self.shard_carry_state(strategy_state)
        if self.rl_fused:
            # the DQN tuner's carry (net params, optimizer state, replay
            # ring, epsilon, delayed-reward anchors) rides strategy_state
            # so it is donated, scanned, and checkpointed exactly like
            # any strategy state
            strategy_state = {"base": strategy_state,
                              "rl": self._rl.init_state(rng)}
        return ServerState(
            params=params,
            opt_state=opt_state,
            strategy_state=strategy_state,
            round=0,
        )

    # ------------------------------------------------------------------
    def shard_carry_state(self, strategy_state: Any) -> Any:
        """Lay the paged carry tables out with the slot axis SHARDED
        over the clients mesh axis (the fleet transfer plane's HBM
        divisor: per-device pool bytes = total / mesh_size) and the
        rest of the state replicated.  Applied at init and again after
        a checkpoint restore, so the donated round program always sees
        one stable layout (no resharding copies, no donation-layout
        churn the recompile sentinel would flag)."""
        if not isinstance(strategy_state, dict):
            raise ValueError(
                "fleet paged carry requires a dict strategy_state with "
                f"the carry tables as keys — got "
                f"{type(strategy_state).__name__}")
        from ..parallel.sharding import slot_pool_sharding
        pool_spec = slot_pool_sharding(self.mesh)
        carry_keys = set(self.strategy.carry_tables)
        # flint: disable=put-loop one-time layout at init/resume, not per-round dispatch
        return {k: jax.device_put(v, pool_spec if k in carry_keys
                                  else self._replicated)
                for k, v in strategy_state.items()}

    # ------------------------------------------------------------------
    def attach_pool(self, pool_arrays: Dict[str, np.ndarray]) -> None:
        """Upload the flat sample pool (``build_sample_pool``) to every
        device ONCE and switch the round program to device-resident mode:
        per-round inputs shrink from gathered feature rows to ``[K,S,B]``
        int32 indices, and the row gather becomes part of the compiled
        program.  The dataloading analogue of keeping params resident —
        the reference re-ships client data from host per round
        (``core/client.py:101-124``); on a remote-attached chip that
        transfer dominates small-model rounds."""
        # flint: disable=put-loop one-time pool upload at attach, not per-round dispatch
        self._pool = {k: jax.device_put(np.asarray(v), self._replicated)
                      for k, v in pool_arrays.items()}
        self._multi_cache = {}
        self._staged_cache = {}
        self._stats_packers = {}
        self._bucket_collect_cache = {}
        self._bucket_collect_core = {}
        self._bucket_finalize = None
        self._round_step = self._build_round_step()

    # ------------------------------------------------------------------
    def _build_round_step(self) -> Callable:
        strategy = self.strategy
        client_update = self.client_update
        stale_prob = self.stale_prob
        mesh = self.mesh
        cspec = P(CLIENTS_AXIS)
        rspec = P()
        pool_mode = self._pool is not None

        clients_per_chunk = self.clients_per_chunk
        # fluteshield statics: all compile-time branches — a config
        # without robust/corruption traces the exact legacy program
        shield = self.shield
        robust_stack = shield is not None and shield.wants_stack
        chaos_corruption = self.chaos_corruption
        corrupt_scale = self._corrupt_scale
        corrupt_flip_scale = self._corrupt_flip_scale
        # fluteflow static: the traced-staleness operand threads AFTER
        # corrupt_mode in every positional order below
        traffic_staleness = self.traffic_staleness
        # universal-overlap statics: both compile-time branches — a
        # config without fused_carry traces the exact legacy program
        device_carry = self.device_carry
        carry_paged = self.carry_paged
        rl_fused = self.rl_fused
        fused_rl = self._rl
        # mesh-sharded page pool (fleet paging x shard_map): the carry
        # tables enter the shard_map as their OWN operand with a
        # P(CLIENTS_AXIS) slot-axis spec (the rest of strategy_state
        # stays replicated), and the GLOBAL carry_slots convert to
        # shard-local indices in-body — the gather/scatter is local to
        # the shard that computes the lane, no cross-shard collective.
        # GSPMD mode keeps global ids and lets the partitioner place
        # the (still slot-axis-sharded) tables.
        carry_split = carry_paged and self.partition_mode == "shard_map"
        carry_keys = tuple(strategy.carry_tables) if carry_paged else ()
        shard_slots = self._carry_shard_slots
        # secure-aggregation statics: wants_cohort routes the default
        # payload through the strategy's mask_parts AFTER corruption
        # (the adversary attacks the float payload the client would
        # transmit; the int32 group element is transport, not target)
        # and masked_screen switches fluteshield to submitted-norm
        # voting (the masked stack carries no plaintext norm signal)
        wants_cohort = bool(getattr(strategy, "wants_cohort", False))
        masked_screen = shield is not None and wants_cohort

        def shard_body(params, strategy_state, arrays, sample_mask,
                       client_mask, client_ids, client_lr, round_idx,
                       leakage_threshold, quant_threshold, rng,
                       cohort_ids=None, cohort_mask=None,
                       carry_slots=None, corrupt_mode=None,
                       staleness=None, pool=None):
            if self.partition_mode == "shard_map":
                # shard-local [K_local] -> full replicated [K] cohort
                # (the median vote and the robust payload stack need
                # every client, not this shard's slice)
                def gather_axis(x):
                    return jax.lax.all_gather(x, CLIENTS_AXIS, axis=0,
                                              tiled=True)
            else:
                def gather_axis(x):
                    return x
            def gather_pool(arrays, sample_mask):
                # device-resident mode: 'arrays' carries pool indices;
                # gather the feature rows in-program (one XLA gather per
                # key, HBM-local — no host bytes moved).  Padding slots
                # index row 0, so zero the gathered rows with the sample
                # mask: padding then holds zeros exactly like host packing
                # (pool-vs-host bit-identity by construction, not by every
                # task loss masking perfectly — tests/test_device_pool.py)
                idx = arrays["__idx__"]
                m = sample_mask
                return {
                    k: pool[k][idx]
                    * m.reshape(m.shape + (1,) * (pool[k].ndim - 1)
                                ).astype(pool[k].dtype)
                    for k in pool}

            def per_client(arr_c, mask_c, cm_c, cid_c, *rest):
                # Deterministic independent stream per (round, client):
                # jax.random.fold_in discipline (SURVEY.md §7 hard parts).
                # rng folds on the TRUE client id even under fleet
                # paging — only the carry table index is remapped.
                rest = list(rest)
                slot_c = rest.pop(0) if carry_paged else cid_c
                corrupt_c = rest.pop(0) if chaos_corruption else None
                stale_c = rest.pop(0) if traffic_staleness else None
                rng_c = jax.random.fold_in(rng, cid_c)
                carry_row = None
                if device_carry:
                    # carry strategies gather their own table rows from
                    # strategy_state by row id (the client id for
                    # resident tables, the page-pool SLOT id under
                    # fleet paging) and return the per-client carry
                    # update row alongside the payload
                    parts, tl, ns, stats, carry_row = \
                        strategy.client_step_carry(
                            client_update, params, arr_c, mask_c,
                            client_lr, rng_c, client_id=slot_c,
                            live_mask=cm_c, round_idx=round_idx,
                            leakage_threshold=leakage_threshold,
                            quant_threshold=quant_threshold,
                            strategy_state=strategy_state,
                            **({"staleness": stale_c} if traffic_staleness
                               else {}))
                else:
                    # traced staleness (fluteflow): the arrival plane's
                    # TRUE broadcast-version gap replaces the strategy's
                    # in-jit staleness model — passed only when the
                    # engine compiled the operand in, so staleness-blind
                    # strategies keep their exact call signature
                    parts, tl, ns, stats = strategy.client_step(
                        client_update, params, arr_c, mask_c, client_lr,
                        rng_c, round_idx=round_idx,
                        leakage_threshold=leakage_threshold,
                        quant_threshold=quant_threshold,
                        strategy_state=strategy_state,
                        **({"staleness": stale_c} if traffic_staleness
                           else {}))
                if chaos_corruption:
                    # adversarial chaos (resilience/chaos.py corrupt
                    # modes, already gated on the live client_mask):
                    # the DEFAULT payload this client would transmit is
                    # what gets corrupted — local training, stats, and
                    # the claimed weight stay honest-looking, exactly
                    # the threat fluteshield screens for
                    pg0, w0 = parts["default"]
                    mult = jnp.where(
                        corrupt_c == CORRUPT_SCALE, corrupt_scale,
                        jnp.where(corrupt_c == CORRUPT_SIGN_FLIP,
                                  -corrupt_flip_scale, 1.0))
                    bad = corrupt_c == CORRUPT_NAN
                    pg0 = jax.tree.map(
                        lambda g: (jnp.where(
                            bad, jnp.asarray(jnp.nan, g.dtype),
                            g * mult.astype(g.dtype))
                            if jnp.issubdtype(g.dtype, jnp.floating)
                            else g), pg0)
                    parts = dict(parts)
                    parts["default"] = (pg0, w0)
                sub_norm = jnp.zeros(())
                if wants_cohort:
                    # secure aggregation: encode + pairwise-mask the
                    # POST-corruption payload toward the round's SAMPLED
                    # cohort (cohort_ids/cohort_mask, replicated); the
                    # returned sub_norm is the submitted-norm scalar a
                    # verified-aggregation server would see — the
                    # shield's masked screening votes on it
                    parts, sub_norm = strategy.mask_parts(
                        parts, cid_c, cm_c, cohort_ids, cohort_mask,
                        round_idx)
                parts = {name: (tree, w * cm_c)
                         for name, (tree, w) in parts.items()}
                if stale_prob > 0.0:
                    coin = jax.random.bernoulli(
                        jax.random.fold_in(rng_c, 3), stale_prob)
                    stale = coin.astype(jnp.float32) * cm_c
                else:
                    stale = jnp.zeros(())
                # carry_row is None (a leafless pytree — vmap passes it
                # through) unless the strategy runs in device-carry mode
                return (parts, tl * cm_c, ns * cm_c, stats, stale,
                        carry_row, sub_norm)

            def process_chunk(arr_k, sm_k, cm_k, cid_k, *rest_k):
                """One chunk of clients -> (summed locals, per-client
                privacy stats, raw parts, effective client mask).  The
                whole shard is one chunk in the default path."""
                rest_k = list(rest_k)
                slot_k = rest_k.pop(0) if carry_paged else None
                corrupt_k = rest_k.pop(0) if chaos_corruption else None
                stale_k = rest_k.pop(0) if traffic_staleness else None
                if pool is not None:
                    arr_k = gather_pool(arr_k, sm_k)
                vmap_args = (arr_k, sm_k, cm_k, cid_k) + \
                    ((slot_k,) if carry_paged else ()) + \
                    ((corrupt_k,) if chaos_corruption else ()) + \
                    ((stale_k,) if traffic_staleness else ())
                parts, tls, nss, stats, stale, carry_rows, sub_norms = \
                    jax.vmap(per_client)(*vmap_args)
                # per-client privacy-attack metrics stay per-client (the
                # server needs the distribution for the adaptive leakage
                # threshold, core/server.py:397-409)
                privacy_per_client = {k: v for k, v in stats.items()
                                      if k.startswith("privacy_")}
                stats = {k: v for k, v in stats.items()
                         if not k.startswith("privacy_")}

                shield_counts = None
                if shield is not None:
                    # fluteshield screening: quarantine from the ACTUAL
                    # would-be-aggregated payloads, then exclude the
                    # quarantined clients from every downstream sum via
                    # jnp.where — a `0 *` multiply would let a NaN leaf
                    # re-poison the very aggregate it was caught in
                    pg_k, w_k = parts["default"]
                    if masked_screen:
                        # masked submissions carry no plaintext norm or
                        # finiteness signal — vote on the per-client
                        # SUBMITTED norms instead (the verified-
                        # aggregation model; robust/shield.py)
                        keep, q_nonfinite, q_norm = shield.screen_masked(
                            sub_norms, tls, w_k, cm_k, gather_axis)
                    else:
                        keep, q_nonfinite, q_norm = shield.screen(
                            pg_k, tls, w_k, cm_k, gather_axis)
                    keep_b = keep > 0
                    pg_k = jax.tree.map(
                        lambda g: jnp.where(
                            keep_b.reshape((-1,) + (1,) * (g.ndim - 1)),
                            g, jnp.zeros_like(g)), pg_k)
                    parts = dict(parts)
                    parts["default"] = (pg_k, jnp.where(keep_b, w_k, 0.0))
                    tls = jnp.where(keep_b, tls, 0.0)
                    nss = jnp.where(keep_b, nss, 0.0)
                    stats = {k: jnp.where(keep_b, v, 0.0)
                             for k, v in stats.items()}
                    # fold into the client mask: counts, stat means, and
                    # aggregation weights renormalize on device exactly
                    # like mesh padding / chaos dropout
                    cm_k = cm_k * keep
                    shield_counts = (jnp.sum(q_nonfinite),
                                     jnp.sum(q_norm))

                local = {"parts": {}}
                for name, (trees, ws) in parts.items():
                    w_now = ws * (1.0 - stale)
                    w_def = ws * stale
                    wsum = lambda w, t: jax.tree.map(
                        lambda g: jnp.tensordot(w, g, axes=[[0], [0]]), t)
                    if name in strategy.unit_weight_parts:
                        # masked payloads: every PRESENT slot enters with
                        # coefficient exactly 1 (else pairwise masks
                        # cannot cancel); the tensordot runs in the
                        # tree's own dtype so int32 modular arithmetic
                        # wraps instead of promoting to float
                        gsum = jax.tree.map(
                            lambda g: jnp.tensordot(
                                cm_k.astype(g.dtype), g, axes=[[0], [0]]),
                            trees)
                        local["parts"][name] = {
                            "grad_sum": gsum,
                            "weight_sum": jnp.sum(w_now),
                            "grad_sum_def": jax.tree.map(
                                jnp.zeros_like, gsum),
                            "weight_sum_def": jnp.sum(w_def),
                            "weight_sum_raw": jnp.sum(ws),
                        }
                        continue
                    local["parts"][name] = {
                        "grad_sum": wsum(w_now, trees),
                        "weight_sum": jnp.sum(w_now),
                        "grad_sum_def": wsum(w_def, trees),
                        "weight_sum_def": jnp.sum(w_def),
                        "weight_sum_raw": jnp.sum(ws),
                    }
                local.update({
                    "train_loss_sum": jnp.sum(tls),
                    "num_samples_sum": jnp.sum(nss),
                    "client_count": jnp.sum(cm_k),
                    "stats_mean_sum": jnp.sum(stats["mean"] * cm_k),
                    "stats_mag_sum": jnp.sum(stats["mag"] * cm_k),
                    "stats_var_sum": jnp.sum(stats["var_corrected"] * cm_k),
                    "stats_norm_sum": jnp.sum(stats["norm"] * cm_k),
                })
                if shield_counts is not None:
                    # per-cause quarantine counters: psum'd with the
                    # other locals and packed into the single-transfer
                    # stats buffer — zero new device_gets
                    local["shield_nonfinite"] = shield_counts[0]
                    local["shield_norm_outlier"] = shield_counts[1]
                extras = {}
                if device_carry:
                    extras["carry"] = carry_rows
                if rl_fused:
                    # the RL tuner needs the full per-client payload stack
                    # (to re-weight) and the reference feature layout
                    # (weight, magnitude, mean, variance per client)
                    extras["rl"] = {
                        "stack": parts["default"][0],
                        "w": parts["default"][1],
                        "mag": stats["mag"], "mean": stats["mean"],
                        "var": stats["var_corrected"],
                    }
                return local, privacy_per_client, parts, cm_k, extras

            k_local = sample_mask.shape[0]
            if clients_per_chunk and clients_per_chunk < k_local:
                if k_local % clients_per_chunk != 0:
                    raise ValueError(
                        f"clients_per_chunk={clients_per_chunk} must divide "
                        f"the per-shard client grid ({k_local}); pad "
                        "num_clients_per_iteration or pick a divisor")

                def to_chunks(x):
                    return x.reshape((k_local // clients_per_chunk,
                                      clients_per_chunk) + x.shape[1:])

                xs = jax.tree.map(to_chunks, (arrays, sample_mask,
                                              client_mask, client_ids) +
                                  ((corrupt_mode,) if chaos_corruption
                                   else ()))

                def scan_body(acc, xs_c):
                    local_c, priv_c, _, _, _ = process_chunk(*xs_c)
                    return jax.tree.map(jnp.add, acc, local_c), priv_c

                zero_local = jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype),
                    jax.eval_shape(lambda c: process_chunk(*c)[0],
                                   jax.tree.map(lambda x: x[0], xs)))
                local, priv_chunks = jax.lax.scan(scan_body, zero_local, xs)
                # [C, chunk] per-client stats back to the flat [K] layout
                privacy_per_client = jax.tree.map(
                    lambda y: y.reshape((-1,) + y.shape[2:]), priv_chunks)
                parts = None  # never materialized across all K — the point
                cm_eff = None
                extras = {}
            else:
                (local, privacy_per_client, parts, cm_eff,
                 extras) = process_chunk(
                    arrays, sample_mask, client_mask, client_ids,
                    *((carry_slots,) if carry_paged else ()),
                    *((corrupt_mode,) if chaos_corruption else ()),
                    *((staleness,) if traffic_staleness else ()))
            if self.partition_mode == "shard_map":
                # the "harvest": one collective instead of K P2P recvs
                total = jax.lax.psum(local, CLIENTS_AXIS)
            else:
                total = local
            if self.dump_norm_stats and parts and "default" in parts:
                # per-client PAYLOAD norm + cosine vs the aggregate
                # direction (reference norm_stats.txt/cosines.txt dumps over
                # client_parameters_stack — i.e. post-transform payloads —
                # core/server.py:392-395, fedavg.py:149-152); the weighted
                # grad SUM has the aggregate's direction, so cosines match
                # the reference's vs-agg values exactly
                pgs, _ = parts["default"]
                gsum = total["parts"]["default"]["grad_sum"]
                dots = jax.tree.map(
                    lambda g, G: jnp.tensordot(
                        g.reshape(g.shape[0], -1), G.reshape(-1), axes=1),
                    pgs, gsum)
                dot = sum(jax.tree.leaves(dots))
                sqs = jax.tree.map(
                    lambda g: jnp.sum(g.reshape(g.shape[0], -1) ** 2, axis=1),
                    pgs)
                pg_norm = jnp.sqrt(sum(jax.tree.leaves(sqs)))
                gnorm = optax.global_norm(gsum)
                privacy_per_client["norm"] = pg_norm
                privacy_per_client["cosine"] = dot / jnp.maximum(
                    pg_norm * gnorm, 1e-12)
            out = (total, privacy_per_client)
            if robust_stack:
                # the Byzantine-robust combine (coordinate-wise trimmed
                # mean / median, strategies/robust.py) needs the full
                # SCREENED per-client payload stack replicated: the
                # estimator's inherent K x model memory cost, paid in
                # HBM inside the program — nothing crosses to the host
                stack_tree = jax.tree.map(gather_axis,
                                          parts["default"][0])
                stack_keep = gather_axis(cm_eff)
                out += (stack_tree, stack_keep)
            if masked_screen:
                # the post-quarantine survivor mask, replicated: the
                # round step needs it to cancel the residual pairwise
                # masks of (survivor, quarantined) edges and to
                # renormalize the decode over survivors only
                out += (gather_axis(cm_eff),)
            if device_carry:
                # replicated full-cohort carry rows: every shard scatters
                # the identical update, so strategy_state stays replicated
                out += (jax.tree.map(gather_axis, extras["carry"]),)
            if rl_fused:
                # full per-client payload stack + feature vectors for the
                # in-program re-weighting (reference keeps
                # client_parameters_stack for this, dga.py:317-330)
                out += (jax.tree.map(gather_axis, extras["rl"]),)
            return out

        def shard_entry(params, strategy_state, arrays, sample_mask,
                        client_mask, client_ids, client_lr, round_idx,
                        leakage_threshold, quant_threshold, rng,
                        cohort_ids, cohort_mask, *rest):
            # trailing operands are positional through shard_map, so
            # which slot means what depends on the compile-time flags —
            # route them to the right keyword here (with corruption off
            # and the pool on, the pool must not land in corrupt_mode)
            rest = list(rest)
            if carry_split:
                # sharded pool: this shard's table block rejoins the
                # replicated state, and the global slot ids drop to
                # block-local (padding stays -1) — the allocator
                # guaranteed every lane's slot lives on this shard
                tables = rest.pop(0)
                strategy_state = {**strategy_state, **tables}
            slots = rest.pop(0) if carry_paged else None
            if carry_split:
                off = jax.lax.axis_index(CLIENTS_AXIS) * shard_slots
                slots = jnp.where(slots >= 0, slots - off, -1)
            corrupt = rest.pop(0) if chaos_corruption else None
            stale = rest.pop(0) if traffic_staleness else None
            pool_arg = rest.pop(0) if pool_mode else None
            return shard_body(params, strategy_state, arrays, sample_mask,
                              client_mask, client_ids, client_lr,
                              round_idx, leakage_threshold,
                              quant_threshold, rng, cohort_ids,
                              cohort_mask, carry_slots=slots,
                              corrupt_mode=corrupt, staleness=stale,
                              pool=pool_arg)

        if self.partition_mode == "shard_map":
            out_specs = (rspec, cspec) + \
                ((rspec, rspec) if robust_stack else ()) + \
                ((rspec,) if masked_screen else ()) + \
                ((rspec,) if device_carry else ()) + \
                ((rspec,) if rl_fused else ())
            sharded_collect = shard_map(
                shard_entry, mesh=mesh,
                in_specs=(rspec, rspec, cspec, cspec, cspec, cspec, rspec,
                          rspec, rspec, rspec, rspec, rspec, rspec) +
                         ((cspec,) if carry_split else ()) +
                         ((cspec,) if carry_paged else ()) +
                         ((cspec,) if chaos_corruption else ()) +
                         ((cspec,) if traffic_staleness else ()) +
                         ((rspec,) if pool_mode else ()),
                out_specs=out_specs, check_vma=False)
        else:
            # GSPMD mode: plain jit — client data stays sharded on the
            # 'clients' axis, params sharded per infer_model_sharding on the
            # 'model' axis; XLA's SPMD partitioner inserts the collectives
            # (enables tensor-parallel BERT, which the reference lacks).
            sharded_collect = shard_entry

        chaos_faults = self.chaos_client_faults

        def round_step(params, opt_state, strategy_state, arrays, sample_mask,
                       client_mask, client_ids, client_lr, server_lr,
                       round_idx, leakage_threshold, quant_threshold, rng,
                       *extra_args):
            # chaos client faults (extra data operands, present only when
            # the engine was built with them): dropout multiplies into
            # client_mask — downstream everything (strategy weights, psum
            # denominators, stats) renormalizes exactly like mesh padding
            # — and straggling truncates sample_mask's step grid, so a
            # straggler's PARTIAL local work still aggregates
            # (CLIP/FedBuff-style partial participation).  The injected-
            # fault counters join round_stats and leave through the same
            # packed single-transfer buffer as every other stat.
            chaos_stats = {}
            # the round's SAMPLED cohort mask, captured BEFORE chaos
            # dropout folds in: secure-aggregation clients mask toward
            # the sampled cohort, so the cancellation pass needs both
            # masks to find the (survivor, lost) edges
            sampled_cm = client_mask
            n_used = 0
            if carry_paged:
                # fleet paging: the host-remapped pool slot per lane —
                # the carry gather/scatter index; everything else keeps
                # using the true client ids
                carry_slots = extra_args[0]
                n_used = 1
            else:
                carry_slots = client_ids
            if chaos_faults:
                chaos_drop, chaos_keep = \
                    extra_args[n_used], extra_args[n_used + 1]
                n_used += 2
                step_live = (jnp.sum(sample_mask, axis=-1) > 0)      # [K, S]
                real_steps = jnp.sum(step_live, axis=-1)             # [K]
                keep_f = (jnp.arange(sample_mask.shape[-2])[None, :]
                          < chaos_keep[:, None]).astype(jnp.float32)  # [K, S]
                live_cm = client_mask * (1.0 - chaos_drop)
                chaos_stats = {
                    "chaos_dropped": jnp.sum(client_mask * chaos_drop),
                    "chaos_straggled": jnp.sum(
                        live_cm * (chaos_keep < real_steps)),
                    "chaos_steps_lost": jnp.sum(
                        step_live.astype(jnp.float32) * (1.0 - keep_f)
                        * live_cm[:, None]),
                }
                sample_mask = sample_mask * keep_f[..., None].astype(
                    sample_mask.dtype)
                client_mask = live_cm
            corrupt_args = ()
            if chaos_corruption:
                # adversarial corruption modes (one more per-round data
                # operand): gated on the LIVE mask — a dropped client
                # never transmits, and a padding slot's zero payload
                # must not be NaN'd into the sum (0-weight x NaN is
                # still NaN through a tensordot)
                corrupt_mode = extra_args[n_used]
                n_used += 1
                corrupt_mode = jnp.where(client_mask > 0, corrupt_mode, 0)
                f32 = jnp.float32
                chaos_stats.update({
                    "chaos_nan_injected": jnp.sum(
                        (corrupt_mode == CORRUPT_NAN).astype(f32)),
                    "chaos_scaled": jnp.sum(
                        (corrupt_mode == CORRUPT_SCALE).astype(f32)),
                    "chaos_sign_flipped": jnp.sum(
                        (corrupt_mode == CORRUPT_SIGN_FLIP).astype(f32)),
                })
                corrupt_args = (corrupt_mode,)
            stale_args = ()
            traffic_stats = {}
            if traffic_staleness:
                # fluteflow traced staleness (one more per-round data
                # operand): gated on the LIVE mask — padding slots and
                # chaos-dropped clients contribute nothing, so their
                # staleness must not count — and binned into the
                # per-staleness histogram that rides the packed stats
                # (the host replay oracle in traffic/schedule.py is the
                # cross-check).  The strategy consumes the TRUE value;
                # only the histogram clips at its last (overflow) bin.
                stale_vec = extra_args[n_used]
                n_used += 1
                stale_vec = jnp.where(client_mask > 0, stale_vec, 0)
                f32 = jnp.float32
                live = (client_mask > 0).astype(f32)
                binned = jnp.minimum(stale_vec, STALE_HIST_BINS - 1)
                traffic_stats = {
                    f"traffic_stale_{b}": jnp.sum(
                        (binned == b).astype(f32) * live)
                    for b in range(STALE_HIST_BINS)}
                traffic_stats["traffic_stale_sum"] = jnp.sum(
                    stale_vec.astype(f32) * live)
                stale_args = (stale_vec,)
            pool_args = extra_args[n_used:]
            # strategies may move the broadcast point off the canonical
            # params (e.g. FedAC's momentum-like md point); default identity
            bcast = strategy.broadcast_params(params, strategy_state)
            if carry_split:
                # the sharded pool tables ride their own cspec operand;
                # everything else in strategy_state stays replicated
                collect_state = {k: v for k, v in strategy_state.items()
                                 if k not in carry_keys}
                carry_tab_args = ({k: strategy_state[k]
                                   for k in carry_keys},)
            else:
                collect_state = strategy_state
                carry_tab_args = ()
            collect_out = sharded_collect(
                bcast, collect_state, arrays, sample_mask, client_mask,
                client_ids, client_lr, round_idx, leakage_threshold,
                quant_threshold, rng, client_ids, sampled_cm,
                *carry_tab_args,
                *((carry_slots,) if carry_paged else ()),
                *corrupt_args, *stale_args, *pool_args)
            collected, privacy_per_client = collect_out[0], collect_out[1]
            pos = 2
            if robust_stack:
                stack_tree, stack_keep = collect_out[pos:pos + 2]
                pos += 2
            if masked_screen:
                survivors = collect_out[pos]
                pos += 1
            if device_carry:
                carry_full = collect_out[pos]
                pos += 1
            if rl_fused:
                rl_pc = collect_out[pos]
                pos += 1
            part_sums = collected["parts"]
            secagg_stats = {}
            if wants_cohort:
                # secure-aggregation mask recovery: subtract the residual
                # one-sided masks of every (survivor, lost) pair so the
                # int32 sum telescopes back to exactly the survivors'
                # encodings.  Both masks are DATA — no dropout pattern
                # recompiles.  Without a shield the survivor set is the
                # post-chaos live mask; quarantine shrinks it further.
                if not masked_screen:
                    survivors = client_mask
                default = dict(part_sums["default"])
                gsum = strategy.cancel_masks(
                    default["grad_sum"], client_ids, sampled_cm,
                    survivors, round_idx)
                f32 = jnp.float32
                secagg_stats = {
                    "secagg_recovered_dropout": jnp.sum(
                        ((sampled_cm > 0) & (client_mask <= 0))
                        .astype(f32)),
                    "secagg_recovered_quarantine": jnp.sum(
                        ((client_mask > 0) & (survivors <= 0))
                        .astype(f32)),
                }
                min_surv = int(getattr(strategy, "min_survivors", 0) or 0)
                if min_surv > 0:
                    # SecAgg's t-of-K liveness floor: too few survivors
                    # aborts the round on device — the aggregate zeroes,
                    # the server step is a no-op, and the abort flag
                    # rides the packed stats
                    abort = (jnp.sum(survivors) <
                             jnp.asarray(min_surv, survivors.dtype))
                    gsum = jax.tree.map(
                        lambda g: g * (1 - abort.astype(g.dtype)), gsum)
                    secagg_stats["secagg_abort"] = abort.astype(f32)
                default["grad_sum"] = gsum
                part_sums = dict(part_sums)
                part_sums["default"] = default
            deferred = None
            if stale_prob > 0.0:
                default = part_sums["default"]
                deferred = {"grad_sum": default["grad_sum_def"],
                            "weight_sum": default["weight_sum_def"]}
            rl_stats = {}
            if robust_stack:
                # Byzantine-robust combine over the screened stack
                # (strategies/robust.py); strategy state passes through
                # untouched — RobustFedAvg is stateless by construction
                agg = strategy.combine_stack(stack_tree, stack_keep,
                                             jax.random.fold_in(rng, 17))
                new_strategy_state = strategy_state
            elif rl_fused:
                # fused RL replaces the combine: the DQN tuner re-weights
                # the gathered payload stack in-program; its whole carry
                # (net, optimizer, replay ring, epsilon, delayed reward)
                # rides strategy_state["rl"] (rl/fused.py)
                cur_loss = collected["train_loss_sum"] / jnp.maximum(
                    collected["client_count"], 1.0)
                agg, new_rl_state, rl_stats = fused_rl.combine(
                    strategy_state["rl"],
                    {k: rl_pc[k] for k in ("w", "mag", "mean", "var")},
                    rl_pc["stack"], cur_loss, jax.random.fold_in(rng, 29))
                new_strategy_state = {"base": strategy_state["base"],
                                      "rl": new_rl_state}
            else:
                agg, new_strategy_state = strategy.combine_parts(
                    part_sums, deferred, strategy_state,
                    jax.random.fold_in(rng, 17),
                    num_clients=collected["client_count"],
                    global_params=bcast)
            if device_carry:
                # scatter the round's per-client carry rows (SCAFFOLD
                # controls / EF residuals / personalization heads) back
                # into the donated strategy_state tables — the round-k ->
                # k+1 dependency the pipeline needed off the host.
                # carry_slots IS client_ids outside fleet paging.
                new_strategy_state = strategy.apply_carry(
                    new_strategy_state, carry_slots, carry_full,
                    rng=jax.random.fold_in(rng, 31))
            if self.server_max_grad_norm is not None:
                agg = _clip_by_global_norm(agg, float(self.server_max_grad_norm))
            if strategy.owns_server_update:
                # multi-sequence schemes (FedAC) apply their own coupled
                # update; the optax state passes through untouched
                new_params, new_strategy_state = strategy.apply_server_update(
                    params, agg, new_strategy_state, server_lr)
                new_opt_state = opt_state
            else:
                # server optimizer over the aggregate pseudo-gradient
                # (reference ModelUpdater.update_model, core/trainer.py:127-137)
                opt_state.hyperparams["learning_rate"] = server_lr
                updates, new_opt_state = self.server_tx.update(
                    agg, opt_state, params)
                new_params = optax.apply_updates(params, updates)
            default_part = part_sums.get("default") or \
                next(iter(part_sums.values()))
            round_stats = {
                "train_loss_sum": collected["train_loss_sum"],
                "num_samples_sum": collected["num_samples_sum"],
                "client_count": collected["client_count"],
                "weight_sum": default_part["weight_sum"],
                "weight_sum_raw": default_part["weight_sum_raw"],
                "grad_mean": collected["stats_mean_sum"] / jnp.maximum(collected["client_count"], 1.0),
                "grad_mag": collected["stats_mag_sum"] / jnp.maximum(collected["client_count"], 1.0),
                "grad_var": collected["stats_var_sum"] / jnp.maximum(collected["client_count"], 1.0),
                "grad_norm": collected["stats_norm_sum"] / jnp.maximum(collected["client_count"], 1.0),
                "agg_grad_norm": optax.global_norm(agg),
            }
            round_stats.update(chaos_stats)
            round_stats.update(traffic_stats)
            round_stats.update(secagg_stats)
            round_stats.update(rl_stats)
            if shield is not None:
                # per-cause quarantine counters out through the same
                # packed single transfer as every other stat
                round_stats["shield_nonfinite"] = \
                    collected["shield_nonfinite"]
                round_stats["shield_norm_outlier"] = \
                    collected["shield_norm_outlier"]
            for k, v in privacy_per_client.items():
                round_stats[k] = v
            if self.devbus.enabled:
                # engine's own publisher: relative APPLIED update size
                # ‖Δθ‖/‖θ‖ — the training-health scalar a grad norm
                # alone hides (a huge gradient into huge weights is
                # fine; into tiny ones is a blow-up).  Δθ is the
                # post-optimizer delta (new - old), NOT the aggregate
                # pseudo-gradient: the server lr / momentum transform
                # scales the actual step, and this scalar must report
                # what was applied.  Published like any strategy scalar
                # and drained into the packed stats below.
                applied = jax.tree.map(lambda a, b: a - b,
                                       new_params, params)
                self.devbus.publish(
                    "update_ratio",
                    optax.global_norm(applied)
                    / (optax.global_norm(new_params) + 1e-12))
                round_stats.update(self.devbus.drain())
            # single-transfer stats: pack the whole stats tree into one
            # 1-D buffer per dtype INSIDE the program (pure reshape/concat,
            # XLA fuses it), so the host fetches one buffer per dtype group
            # per round instead of ~a dozen scalars.  The packer (the slot
            # table the host decodes with) is recorded at trace time under
            # a key both sides can compute from the round geometry alone —
            # for one engine the stats tree is a function of K only.
            packer = FlatPacker(round_stats)
            # sample_mask is [K, S, B] here (scan slices the leading round
            # axis off before core runs), so K = shape[-3].  Deliberate
            # trace-time effect: the packer IS this trace's slot table —
            # written once per compile, read only by the host decoder.
            # flint: disable=jit-purity trace-time slot-table recording is the flatpack contract (one write per compile, host-side reads only)
            self._stats_packers[("single", sample_mask.shape[-3])] = packer
            return (new_params, new_opt_state, new_strategy_state,
                    packer.pack(round_stats))

        self._round_step_core = round_step
        return self._instrument(
            "round_step", jax.jit(round_step, donate_argnums=(0, 1, 2)))

    # ------------------------------------------------------------------
    def _multi_core(self, num_rounds: int) -> Callable:
        """The un-jitted ``lax.scan``-over-rounds program body — shared by
        the legacy per-leaf dispatch (``_multi_round_fn`` jits it
        directly) and the staged single-buffer dispatch (which wraps it
        in the unpacking jit)."""
        core = self._round_step_core
        chaos_faults = self.chaos_client_faults
        chaos_corruption = self.chaos_corruption
        n_extra = (1 if self.carry_paged else 0) + \
            (2 if chaos_faults else 0) + \
            (1 if chaos_corruption else 0) + \
            (1 if self.traffic_staleness else 0)

        def multi(params, opt_state, strategy_state, arrays, sample_mask,
                  client_mask, client_ids, client_lrs, server_lrs,
                  round_idxs, leakage_threshold, quant_thresholds, rngs,
                  *extra_args):
            # per-round trailing operands — carry slots ([R, K], fleet
            # paging) then chaos drop/keep and/or corrupt modes — scan
            # with the rest of the round inputs; the resident pool
            # stays a carried constant
            chaos_args = extra_args[:n_extra]
            pool_args = extra_args[n_extra:]

            def body(carry, xs):
                p, o, s = carry
                arr, sm, cm, cid, clr, slr, ridx, qt, rng = xs[:9]
                chaos_xs = xs[9:]
                p, o, s, stats = core(p, o, s, arr, sm, cm, cid, clr, slr,
                                      ridx, leakage_threshold, qt, rng,
                                      *chaos_xs, *pool_args)
                return (p, o, s), stats

            xs = (arrays, sample_mask, client_mask, client_ids,
                  client_lrs, server_lrs, round_idxs, quant_thresholds,
                  rngs) + tuple(chaos_args)
            (p, o, s), stats = jax.lax.scan(
                body, (params, opt_state, strategy_state), xs)
            return p, o, s, stats

        return multi

    def _multi_round_fn(self, num_rounds: int) -> Callable:
        """Jitted ``lax.scan`` over ``num_rounds`` federated rounds.

        TPU-first perf feature with no reference equivalent: FLUTE pays a
        full server<->worker protocol exchange per round
        (``core/federated.py:281-424``); even our single-round program pays
        one host dispatch per round, which dominates when the controller is
        far from the chips.  Scanning R rounds inside one program amortizes
        dispatch/transfer to once per R rounds; client sampling stays
        host-side (it is data-independent lookahead), eval boundaries cap R.
        """
        cached = self._multi_cache.get(num_rounds)
        if cached is not None:
            return cached
        fn = self._instrument(
            f"multi_round_r{num_rounds}",
            jax.jit(self._multi_core(num_rounds), donate_argnums=(0, 1, 2)),
            rounds=num_rounds)
        self._multi_cache[num_rounds] = fn
        return fn

    # ------------------------------------------------------------------
    # RL support: a round variant that also returns per-client payloads so
    # the meta-aggregator can re-weight them (reference keeps
    # client_parameters_stack for this, core/strategies/dga.py:317-330).
    def _build_payload_step(self, with_offsets: bool = False):
        strategy = self.strategy
        client_update = self.client_update
        mesh = self.mesh
        cspec = P(CLIENTS_AXIS)
        rspec = P()

        def shard_body(params, strategy_state, arrays, sample_mask,
                       client_mask, client_ids, client_lr, rng,
                       leakage_threshold, offsets_flat=None):
            def per_client(arr_c, mask_c, cm_c, cid_c, off_c):
                rng_c = jax.random.fold_in(rng, cid_c)
                off_tree = None
                if off_c is not None:
                    from jax.flatten_util import ravel_pytree
                    _, unravel = ravel_pytree(params)
                    off_tree = unravel(off_c)
                parts, tl, ns, stats = strategy.client_step(
                    client_update, params, arr_c, mask_c, client_lr, rng_c,
                    leakage_threshold=leakage_threshold,
                    strategy_state=strategy_state, grad_offset=off_tree)
                pg, w = parts["default"]
                return pg, w * cm_c, tl * cm_c, stats
            return jax.vmap(per_client, in_axes=(0, 0, 0, 0,
                                                 0 if with_offsets else None))(
                arrays, sample_mask, client_mask, client_ids, offsets_flat)

        fn = shard_map(shard_body, mesh=mesh,
                       in_specs=(rspec, rspec, cspec, cspec, cspec, cspec,
                                 rspec, rspec, rspec) +
                                ((cspec,) if with_offsets else ()),
                       out_specs=cspec, check_vma=False)
        return jax.jit(fn)

    def client_payloads(self, state: ServerState, batch: RoundBatch,
                        client_lr: float, rng: jax.Array,
                        grad_offsets: Optional[np.ndarray] = None,
                        leakage_threshold: Optional[float] = None):
        """Per-client ``(pseudo_grad [K,...], weight [K], train_loss [K],
        stats [K])`` — the payload program behind RL re-weighting
        (reference keeps ``client_parameters_stack``, ``dga.py:317-330``)
        and SCAFFOLD control-variate rounds.

        ``grad_offsets`` (optional ``[K, n_params]`` flat f32 array) is the
        per-client drift correction added to every local step's gradient
        (SCAFFOLD's ``c - c_i``); rows for padding clients must be zero.
        ``leakage_threshold`` enables the same privacy-leakage client
        dropping the fused round applies (``wt=0`` above threshold).
        """
        key = "_payload_step_off" if grad_offsets is not None \
            else "_payload_step"
        if not hasattr(self, key):
            setattr(self, key, self._instrument(
                key.lstrip("_"), self._build_payload_step(
                    with_offsets=grad_offsets is not None)))
        args = [
            state.params, state.strategy_state,
            # flint: disable=put-loop host-orchestrated legacy round path; fused_carry is the staged overlap path
            {k: jax.device_put(v, self._client_sharding)
             for k, v in batch.arrays.items()},
            jax.device_put(batch.sample_mask, self._client_sharding),
            jax.device_put(batch.client_mask, self._client_sharding),
            jax.device_put(batch.client_ids, self._client_sharding),
            jnp.asarray(client_lr, jnp.float32), rng,
            jnp.asarray(leakage_threshold if leakage_threshold is not None
                        else jnp.inf, jnp.float32),
        ]
        if grad_offsets is not None:
            # device arrays (DeviceControlTable.offsets) pass through —
            # np.asarray would round-trip the matrix via the host; numpy
            # goes through a sharded put directly (staging via jnp.asarray
            # would commit the whole [K, n_params] matrix to one device)
            if not isinstance(grad_offsets, jax.Array):
                grad_offsets = np.asarray(grad_offsets, np.float32)
            args.append(jax.device_put(grad_offsets, self._client_sharding))
        fn = getattr(self, key)
        out = fn(*args)
        self._note_compiles(key.lstrip("_"), fn)
        return out

    def apply_custom_weights(self, state: ServerState, pgs, weights,
                             server_lr: float) -> ServerState:
        """Aggregate per-client payloads with externally chosen weights and
        take a server step — the RL re-aggregation
        (``sum pg_k * w_k / sum w_k``, reference ``dga.py:317-332``)."""
        if not hasattr(self, "_custom_agg"):
            server_tx = self.server_tx

            def agg_fn(params, opt_state, pgs, weights, server_lr):
                wsum = jnp.maximum(jnp.sum(weights), 1e-12)
                agg = jax.tree.map(
                    lambda g: jnp.tensordot(weights, g, axes=[[0], [0]]) / wsum,
                    pgs)
                if self.server_max_grad_norm is not None:
                    agg = _clip_by_global_norm(
                        agg, float(self.server_max_grad_norm))
                opt_state.hyperparams["learning_rate"] = server_lr
                updates, new_opt = server_tx.update(agg, opt_state, params)
                return optax.apply_updates(params, updates), new_opt

            self._custom_agg = self._instrument("custom_agg",
                                                jax.jit(agg_fn))
        params, opt_state = self._custom_agg(
            state.params, state.opt_state, pgs,
            jax.device_put(jnp.asarray(weights, jnp.float32),
                           self._client_sharding),
            jnp.asarray(server_lr, jnp.float32))
        self._note_compiles("custom_agg", self._custom_agg)
        return ServerState(params, opt_state, state.strategy_state,
                           state.round + 1)

    # ------------------------------------------------------------------
    def _chaos_host(self, chaos_vecs: Optional[list],
                    stacked: bool) -> tuple:
        """Validate + assemble the per-round fault/staleness vectors as
        HOST numpy arrays, one per trailing program operand: per round
        ``(drop [K], keep_steps [K])`` when client faults compiled in,
        followed by ``(corrupt_mode [K],)`` when corruption compiled in,
        followed by ``(staleness [K],)`` when traced staleness compiled
        in (fluteflow) — or nothing when the engine compiled without
        any.  Mismatches are programming errors and raise."""
        dtypes = ([np.float32, np.float32] if self.chaos_client_faults
                  else []) + \
                 ([np.int32] if self.chaos_corruption else []) + \
                 ([np.int32] if self.traffic_staleness else [])
        if not dtypes:
            if chaos_vecs:
                raise ValueError(
                    "chaos vectors supplied but the engine was built "
                    "without chaos client faults, corruption, or traced "
                    "staleness (server_config.chaos / traffic)")
            return ()
        if not chaos_vecs:
            raise ValueError(
                "engine built with chaos client faults/corruption/"
                "traced staleness: every dispatch needs the per-round "
                "vectors")
        if any(len(entry) != len(dtypes) for entry in chaos_vecs):
            raise ValueError(
                f"chaos vector arity mismatch: engine expects "
                f"{len(dtypes)} per-round vectors "
                f"(faults={self.chaos_client_faults}, "
                f"corruption={self.chaos_corruption}, "
                f"staleness={self.traffic_staleness})")
        out = []
        for i, dt in enumerate(dtypes):
            vals = [np.asarray(entry[i], dt) for entry in chaos_vecs]
            out.append(np.stack(vals) if stacked else vals[0])
        return tuple(out)

    def _stage_chaos(self, chaos_vecs: Optional[list], sharding,
                     stacked: bool) -> tuple:
        """Legacy (``input_staging: false``) per-leaf device staging of
        the chaos operands."""
        # flint: disable=put-loop legacy non-staged dispatch path, kept for the staging A/B (tools/dispatch_cost_probe.py)
        return tuple(jax.device_put(arr, sharding)
                     for arr in self._chaos_host(chaos_vecs, stacked))

    # ------------------------------------------------------------------
    # single-buffer input staging (server_config.input_staging, default
    # on): the dispatch half of the flatpack idea.  Everything the host
    # assembles per round — the feature (or index) grids, sample/client
    # masks, client ids, chaos fault vectors, and the lr/round/threshold
    # scalars — crosses the host boundary as ONE buffer per dtype group
    # (clients-axis operands via AxisPacker, replicated scalars via
    # ScalarStager); the inverse runs INSIDE the jitted program as static
    # slices/reshapes XLA fuses away, so the math is bit-identical to the
    # legacy per-leaf path (tests/test_input_staging.py pins both the
    # equivalence and the transfer count).
    # ------------------------------------------------------------------
    def _build_staged_fn(self, R: int, ax_packer: AxisPacker,
                         stager: ScalarStager) -> Callable:
        stacked = R > 1
        core = self._multi_core(R) if stacked else self._round_step_core

        carry_paged = self.carry_paged

        def staged(params, opt_state, strategy_state, ax_bufs, sc_bufs,
                   rng, *pool_args):
            ax = ax_packer.unpack(ax_bufs)
            sc = stager.unpack(sc_bufs)
            carry = (ax["carry_slots"],) if carry_paged else ()
            chaos = ax.get("chaos", ())
            if not stacked:
                return core(params, opt_state, strategy_state,
                            ax["arrays"], ax["sample_mask"],
                            ax["client_mask"], ax["client_ids"],
                            sc["client_lr"], sc["server_lr"],
                            sc["round_idx"], sc["leakage"], sc["quant"],
                            rng, *carry, *chaos, *pool_args)
            # splitting inside the trace produces the same keys the
            # legacy path computed eagerly — split is a pure function
            rngs = jax.random.split(rng, R)
            return core(params, opt_state, strategy_state, ax["arrays"],
                        ax["sample_mask"], ax["client_mask"],
                        ax["client_ids"], sc["client_lr"], sc["server_lr"],
                        sc["round_idx"], sc["leakage"], sc["quant"], rngs,
                        *carry, *chaos, *pool_args)

        return jax.jit(staged, donate_argnums=(0, 1, 2))

    def _dispatch_staged(self, state: ServerState, batches: list,
                         client_lrs: list, server_lrs: list,
                         rng: jax.Array,
                         leakage_threshold: Optional[float],
                         quant_thresholds: Optional[list],
                         chaos_vecs: Optional[list]
                         ) -> Tuple[ServerState, PackedStats]:
        """Staged dispatch of ``len(batches)`` rounds: assemble host-side,
        pack per dtype group, one ``device_put`` for the clients-axis
        groups and one for the scalar groups, run the unpacking jit."""
        R = len(batches)
        stacked = R > 1

        def stack(pick):
            vals = [pick(b) for b in batches]
            return vals[0] if R == 1 else np.stack(vals)

        arrays_host, pool_args = self._host_arrays(batches)
        axis_tree = {
            "arrays": arrays_host,
            "sample_mask": stack(lambda b: b.sample_mask),
            "client_mask": stack(lambda b: b.client_mask),
            "client_ids": stack(lambda b: b.client_ids),
        }
        if self.carry_paged:
            axis_tree["carry_slots"] = stack(self._batch_slots)
        chaos_host = self._chaos_host(chaos_vecs, stacked)
        if chaos_host:
            axis_tree["chaos"] = tuple(chaos_host)
        lr_dt, rd_dt = np.float32, np.int32
        if stacked:
            sc_tree = {
                "client_lr": np.asarray(client_lrs, lr_dt),
                "server_lr": np.asarray(server_lrs, lr_dt),
                "round_idx": np.arange(state.round, state.round + R,
                                       dtype=rd_dt),
                "leakage": lr_dt(leakage_threshold
                                 if leakage_threshold is not None
                                 else np.inf),
                "quant": np.asarray(quant_thresholds
                                    if quant_thresholds is not None
                                    else [-1.0] * R, lr_dt),
            }
        else:
            sc_tree = {
                "client_lr": lr_dt(client_lrs[0]),
                "server_lr": lr_dt(server_lrs[0]),
                "round_idx": rd_dt(state.round),
                "leakage": lr_dt(leakage_threshold
                                 if leakage_threshold is not None
                                 else np.inf),
                "quant": lr_dt(quant_thresholds[0]
                               if quant_thresholds is not None else -1.0),
            }
        ax_packer = AxisPacker(axis_tree, lead_ndim=2 if stacked else 1)
        stager = ScalarStager(sc_tree)
        key = (R, ax_packer.signature, stager.signature)
        fn = self._staged_cache.get(key)
        if fn is None:
            fn = self._instrument(f"staged_r{R}",
                                  self._build_staged_fn(R, ax_packer,
                                                        stager),
                                  rounds=R)
            self._staged_cache[key] = fn
        ax_bufs = ax_packer.pack_np(axis_tree)
        sc_bufs = stager.pack_np(sc_tree)
        ax_sharding = (NamedSharding(self.mesh, P(None, CLIENTS_AXIS))
                       if stacked else self._client_sharding)
        # ONE staging transfer per dtype group: each put runs on the
        # whole per-dtype dict, so the transfer count equals the group
        # count — the dispatch-cost contract the tier-1 guard pins
        ax_dev = jax.device_put(ax_bufs, ax_sharding)
        sc_dev = jax.device_put(sc_bufs, self._replicated)
        self.last_dispatch_puts = len(ax_bufs) + len(sc_bufs)
        self.last_staged_bytes = int(
            sum(b.nbytes for b in ax_bufs.values()) +
            sum(b.nbytes for b in sc_bufs.values()))
        params, opt_state, strategy_state, vecs = fn(
            state.params, state.opt_state, state.strategy_state, ax_dev,
            sc_dev, rng, *pool_args)
        self._note_compiles(f"staged_r{R}", fn)
        new_state = ServerState(params, opt_state, strategy_state,
                                state.round + R)
        packer = self._stats_packers[
            ("single", batches[0].sample_mask.shape[0])]
        return new_state, PackedStats(vecs, packer, rounds=R,
                                      stacked=stacked)

    # ------------------------------------------------------------------
    def run_round(self, state: ServerState, batch: RoundBatch,
                  client_lr: float, server_lr: float,
                  rng: jax.Array,
                  leakage_threshold: Optional[float] = None,
                  quant_threshold: Optional[float] = None,
                  chaos_vecs: Optional[list] = None
                  ) -> Tuple[ServerState, PackedStats]:
        """Stage one round's data onto the mesh and execute the program.

        Dispatch is async; the returned :class:`PackedStats` is a lazy
        handle — nothing crosses the host boundary until ``.fetch()``.
        """
        if self.input_staging:
            return self._dispatch_staged(
                state, [batch], [client_lr], [server_lr], rng,
                leakage_threshold,
                [quant_threshold] if quant_threshold is not None else None,
                chaos_vecs)
        chaos_args = self._stage_chaos(chaos_vecs, self._client_sharding,
                                       stacked=False)
        carry_args = ()
        if self.carry_paged:
            carry_args = (jax.device_put(self._batch_slots(batch),
                                         self._client_sharding),)
        arrays, pool_args = self._stage_arrays([batch], self._client_sharding)
        sample_mask = jax.device_put(batch.sample_mask, self._client_sharding)
        client_mask = jax.device_put(batch.client_mask, self._client_sharding)
        client_ids = jax.device_put(batch.client_ids, self._client_sharding)
        # legacy-dispatch observability: one put per chaos operand +
        # per array key + the three grids, plus the five jnp.asarray
        # scalar transfers below (what staged mode collapses per dtype)
        self.last_dispatch_puts = len(chaos_args) + len(arrays) + 3 + 5
        self.last_staged_bytes = int(
            sum(int(a.nbytes) for a in chaos_args) +
            sum(int(a.nbytes) for a in arrays.values()) +
            sample_mask.nbytes + client_mask.nbytes + client_ids.nbytes)

        params, opt_state, strategy_state, vecs = self._round_step(
            state.params, state.opt_state, state.strategy_state,
            arrays, sample_mask, client_mask, client_ids,
            jnp.asarray(client_lr, jnp.float32),
            jnp.asarray(server_lr, jnp.float32),
            jnp.asarray(state.round, jnp.int32),
            jnp.asarray(leakage_threshold if leakage_threshold is not None
                        else jnp.inf, jnp.float32),
            jnp.asarray(quant_threshold if quant_threshold is not None
                        else -1.0, jnp.float32), rng, *carry_args,
            *chaos_args, *pool_args)
        self._note_compiles("round_step", self._round_step)
        new_state = ServerState(params, opt_state, strategy_state,
                                state.round + 1)
        packer = self._stats_packers[("single", batch.sample_mask.shape[0])]
        return new_state, PackedStats(vecs, packer, rounds=1, stacked=False)

    # ------------------------------------------------------------------
    @staticmethod
    def _batch_slots(batch) -> np.ndarray:
        """The batch's fleet page-pool slot vector; a paged-carry
        dispatch without one is a programming error (the pager sets it
        at prepare time) — fail loudly instead of gathering garbage."""
        slots = getattr(batch, "carry_slots", None)
        if slots is None:
            raise ValueError(
                "fleet paged carry: batch has no carry_slots — the "
                "CarryPager must prepare every chunk before dispatch")
        return np.asarray(slots, np.int32)

    # ------------------------------------------------------------------
    def _host_arrays(self, batches: list) -> Tuple[Dict[str, np.ndarray],
                                                   tuple]:
        """Assemble the data inputs of one round (``[batch]``) or a fused
        chunk (stacked on a leading round axis) as HOST numpy arrays.

        Host-packed ``RoundBatch``es carry their gathered feature arrays;
        ``IndexRoundBatch``es carry only the int32 index grid and ride the
        resident pool (``attach_pool``) as a trailing program operand.
        """
        from ..data.batching import IndexRoundBatch
        is_idx = isinstance(batches[0], IndexRoundBatch)
        if is_idx != (self._pool is not None):
            raise ValueError(
                "round engine pool mode mismatch: "
                f"batch={'indices' if is_idx else 'arrays'} but pool "
                f"{'attached' if self._pool is not None else 'absent'}")

        def stack(pick):
            vals = [pick(b) for b in batches]
            return vals[0] if len(vals) == 1 else np.stack(vals)

        if is_idx:
            return {"__idx__": stack(lambda b: b.indices)}, (self._pool,)
        return {k: stack(lambda b: b.arrays[k])
                for k in batches[0].arrays}, ()

    def _stage_arrays(self, batches: list, sharding):
        """Legacy (``input_staging: false``) per-leaf device staging of
        the round's data inputs."""
        host, pool_args = self._host_arrays(batches)
        # flint: disable=put-loop legacy non-staged dispatch path, kept for the staging A/B (tools/dispatch_cost_probe.py)
        return {k: jax.device_put(v, sharding)
                for k, v in host.items()}, pool_args

    # ------------------------------------------------------------------
    def dispatch_rounds(self, state: ServerState, batches: list,
                        client_lrs: list, server_lrs: list,
                        rng: jax.Array,
                        leakage_threshold: Optional[float] = None,
                        quant_thresholds: Optional[list] = None,
                        chaos_vecs: Optional[list] = None
                        ) -> Tuple[ServerState, PackedStats]:
        """Dispatch ``len(batches)`` rounds as ONE device program (the
        single-round program for R==1, a scan otherwise) WITHOUT blocking:
        the returned state is the async program output and the stats are a
        lazy :class:`PackedStats` handle.  This is the dispatch half of
        the server's software-pipelined loop — the host is free to consume
        the previous chunk's results while this one executes."""
        R = len(batches)
        if self.input_staging:
            return self._dispatch_staged(
                state, batches, client_lrs, server_lrs, rng,
                leakage_threshold, quant_thresholds, chaos_vecs)
        if R == 1:
            return self.run_round(
                state, batches[0], client_lrs[0], server_lrs[0], rng,
                leakage_threshold=leakage_threshold,
                quant_threshold=(quant_thresholds[0] if quant_thresholds
                                 else None),
                chaos_vecs=chaos_vecs)
        stacked_sharding = NamedSharding(self.mesh, P(None, CLIENTS_AXIS))
        chaos_args = self._stage_chaos(chaos_vecs, stacked_sharding,
                                       stacked=True)
        carry_args = ()
        if self.carry_paged:
            carry_args = (jax.device_put(
                np.stack([self._batch_slots(b) for b in batches]),
                stacked_sharding),)
        arrays, pool_args = self._stage_arrays(batches, stacked_sharding)
        sample_mask = jax.device_put(
            np.stack([b.sample_mask for b in batches]), stacked_sharding)
        client_mask = jax.device_put(
            np.stack([b.client_mask for b in batches]), stacked_sharding)
        client_ids = jax.device_put(
            np.stack([b.client_ids for b in batches]), stacked_sharding)
        self.last_dispatch_puts = len(chaos_args) + len(arrays) + 3 + 5
        self.last_staged_bytes = int(
            sum(int(a.nbytes) for a in chaos_args) +
            sum(int(a.nbytes) for a in arrays.values()) +
            sample_mask.nbytes + client_mask.nbytes + client_ids.nbytes)
        rngs = jax.random.split(rng, R)

        fn = self._multi_round_fn(R)
        params, opt_state, strategy_state, vecs = fn(
            state.params, state.opt_state, state.strategy_state,
            arrays, sample_mask, client_mask, client_ids,
            jnp.asarray(client_lrs, jnp.float32),
            jnp.asarray(server_lrs, jnp.float32),
            jnp.arange(state.round, state.round + R, dtype=jnp.int32),
            jnp.asarray(leakage_threshold if leakage_threshold is not None
                        else jnp.inf, jnp.float32),
            jnp.asarray(quant_thresholds if quant_thresholds is not None
                        else [-1.0] * R, jnp.float32), rngs, *carry_args,
            *chaos_args, *pool_args)
        self._note_compiles(f"multi_round_r{R}", fn)
        new_state = ServerState(params, opt_state, strategy_state,
                                state.round + R)
        # the scan stacks the core program's packed per-round vecs into
        # [R, n] buffers; the slot table is the single-round packer the
        # core trace recorded (the scan body traced it just above)
        packer = self._stats_packers[
            ("single", batches[0].sample_mask.shape[0])]
        return new_state, PackedStats(vecs, packer, rounds=R, stacked=True)

    # ------------------------------------------------------------------
    # cohort shape-bucketing (server_config.cohort_bucketing): one
    # COMPACT [K_b, S_b, B, ...] collect program per step bucket + one
    # finalize program per round that combines the per-bucket partials
    # into the weighted aggregate ON DEVICE.  The per-client math is the
    # fused round's exactly (client rng streams fold on client id, and
    # masked padding steps are no-op-pinned), so per-client updates are
    # bit-identical to the monolithic grid; only the summation
    # association differs, in a DETERMINISTIC left-to-right bucket
    # order.  Compiled-program economics: one collect variant per
    # distinct (K_b, S_b) grid — S_b values come from the config-bounded
    # boundary set and K_b is pow2-quantized by the server — plus one
    # finalize variant per bucket-shape signature; the PR 7 recompile
    # sentinel watches that this set stays closed after warmup.
    # ------------------------------------------------------------------
    def _get_bucket_collect_core(self, mega: bool = False) -> Callable:
        """The un-jitted one-bucket collect body (shared by every staged
        per-shape variant): chaos fold + vmap'd client math + either the
        psum'd partial sums (default) or the gathered per-client stack
        (shield mode, where screening must see the WHOLE cohort and so
        defers to the finalize program).

        ``mega`` builds the MEGABATCH variant: two extra lane-sharded
        tape operands (ptr/seg), the heavy training replaced by the
        segment-carrying lane scan run once per ``megabatch_passes``
        spec, and the vmap'd client body kept — unchanged strategy
        weight/transform/carry/stale/corruption math — but fed a FAKE
        client_update that hands back the lane scan's per-client rows."""
        cached = self._bucket_collect_core.get(mega)
        if cached is not None:
            return cached
        strategy = self.strategy
        client_update = self.client_update
        mega_update = self.mega_update
        stale_prob = self.stale_prob
        mesh = self.mesh
        cspec = P(CLIENTS_AXIS)
        rspec = P()
        pool_mode = self._pool is not None
        shield = self.shield
        defer_screen = shield is not None
        chaos_faults = self.chaos_client_faults
        chaos_corruption = self.chaos_corruption
        corrupt_scale = self._corrupt_scale
        corrupt_flip_scale = self._corrupt_flip_scale
        # fluteflow: the traced-staleness operand threads after
        # corrupt_mode per bucket, exactly like the monolithic round
        traffic_staleness = self.traffic_staleness
        device_carry = self.device_carry
        carry_paged = self.carry_paged
        # mesh-sharded page pool: same split as the monolithic round —
        # tables ride a cspec operand, global slots drop to shard-local
        carry_split = carry_paged and self.partition_mode == "shard_map"
        carry_keys = tuple(strategy.carry_tables) if carry_paged else ()
        shard_slots = self._carry_shard_slots
        # secure aggregation x bucketing: each bucket runs its OWN
        # pairwise-mask graph over the bucket's sampled sub-cohort (two
        # replicated operands — the bucket's ids and sampled mask);
        # residual-mask cancellation happens per bucket in finalize.
        # The int32 telescoping is exact either way, so the decoded
        # aggregate is bit-identical to the monolithic round's.
        wants_cohort = bool(getattr(strategy, "wants_cohort", False))

        def shard_body(params, strategy_state, arrays, sample_mask,
                       client_mask, client_ids, client_lr, round_idx,
                       leakage_threshold, quant_threshold, rng,
                       cohort_ids=None, cohort_mask=None,
                       carry_slots=None, corrupt_mode=None,
                       staleness=None, pool=None,
                       ptr=None, seg=None):
            if self.partition_mode == "shard_map":
                def gather_axis(x):
                    return jax.lax.all_gather(x, CLIENTS_AXIS, axis=0,
                                              tiled=True)
            else:
                def gather_axis(x):
                    return x

            def gather_pool(arrays, sample_mask):
                # device-resident mode: identical to the round program's
                # in-program row gather (padding slots zeroed via mask)
                idx = arrays["__idx__"]
                m = sample_mask
                return {
                    k: pool[k][idx]
                    * m.reshape(m.shape + (1,) * (pool[k].ndim - 1)
                                ).astype(pool[k].dtype)
                    for k in pool}

            def per_client(arr_c, mask_c, cm_c, cid_c, *rest):
                # SAME per-client stream discipline as the fused round:
                # fold_in on the CLIENT ID, so a client's rng (and hence
                # its whole local update) is independent of which grid
                # slot or bucket it landed in — the bit-identity anchor
                rest = list(rest)
                slot_c = rest.pop(0) if carry_paged else cid_c
                corrupt_c = rest.pop(0) if chaos_corruption else None
                stale_c = rest.pop(0) if traffic_staleness else None
                rng_c = jax.random.fold_in(rng, cid_c)
                if mega:
                    # fake-update replay: the lane scan already trained
                    # this client — hand its harvested rows back through
                    # the client_update interface, so the strategy's
                    # weight/transform/carry code runs UNCHANGED.  The
                    # trace-time call counter maps the strategy's i-th
                    # client_update call to its i-th megabatch pass
                    # (personalization's global+local double train).
                    mega_c = tuple(rest)
                    calls = {"n": 0}

                    def update_fn(gp, arr, mask, lr_, r_,
                                  grad_offset=None):
                        i = calls["n"]
                        calls["n"] += 1
                        if i >= len(mega_c):
                            raise ValueError(
                                f"{type(strategy).__name__} issued more "
                                "client_update calls than its "
                                "megabatch_passes declared — extend the "
                                "hook or set supports_megabatch = False")
                        pg_i, tl_i, ns_i, st_i = mega_c[i]
                        return pg_i, tl_i, ns_i, dict(st_i)
                else:
                    update_fn = client_update
                carry_row = None
                if device_carry:
                    parts, tl, ns, stats, carry_row = \
                        strategy.client_step_carry(
                            update_fn, params, arr_c, mask_c,
                            client_lr, rng_c, client_id=slot_c,
                            live_mask=cm_c, round_idx=round_idx,
                            leakage_threshold=leakage_threshold,
                            quant_threshold=quant_threshold,
                            strategy_state=strategy_state,
                            **({"staleness": stale_c} if traffic_staleness
                               else {}))
                else:
                    parts, tl, ns, stats = strategy.client_step(
                        update_fn, params, arr_c, mask_c, client_lr,
                        rng_c, round_idx=round_idx,
                        leakage_threshold=leakage_threshold,
                        quant_threshold=quant_threshold,
                        strategy_state=strategy_state,
                        **({"staleness": stale_c} if traffic_staleness
                           else {}))
                if chaos_corruption:
                    pg0, w0 = parts["default"]
                    mult = jnp.where(
                        corrupt_c == CORRUPT_SCALE, corrupt_scale,
                        jnp.where(corrupt_c == CORRUPT_SIGN_FLIP,
                                  -corrupt_flip_scale, 1.0))
                    bad = corrupt_c == CORRUPT_NAN
                    pg0 = jax.tree.map(
                        lambda g: (jnp.where(
                            bad, jnp.asarray(jnp.nan, g.dtype),
                            g * mult.astype(g.dtype))
                            if jnp.issubdtype(g.dtype, jnp.floating)
                            else g), pg0)
                    parts = dict(parts)
                    parts["default"] = (pg0, w0)
                sub_norm = jnp.zeros(())
                if wants_cohort:
                    # encode + mask the post-corruption payload toward
                    # the BUCKET's sampled sub-cohort (same per-client
                    # math as the fused round — bucket placement cannot
                    # perturb a client's encoding, only its mask graph,
                    # and masks cancel exactly)
                    parts, sub_norm = strategy.mask_parts(
                        parts, cid_c, cm_c, cohort_ids, cohort_mask,
                        round_idx)
                parts = {name: (tree, w * cm_c)
                         for name, (tree, w) in parts.items()}
                if stale_prob > 0.0:
                    coin = jax.random.bernoulli(
                        jax.random.fold_in(rng_c, 3), stale_prob)
                    stale = coin.astype(jnp.float32) * cm_c
                else:
                    stale = jnp.zeros(())
                return (parts, tl * cm_c, ns * cm_c, stats, stale,
                        carry_row, sub_norm)

            if pool is not None:
                arrays = gather_pool(arrays, sample_mask)
            mega_rows = ()
            if mega:
                # one lane scan per strategy pass — the MXU-saturating
                # training; per-client rng still folds on TRUE client
                # ids inside the scan, so slot/bucket placement cannot
                # perturb a client's update
                slots_k = carry_slots if carry_paged else client_ids
                passes = strategy.megabatch_passes(
                    strategy_state=strategy_state, global_params=params,
                    client_ids=client_ids, slots=slots_k, rng=rng)
                mega_rows = tuple(
                    mega_update(params, arrays, sample_mask, client_ids,
                                ptr, seg, client_lr, rng,
                                init_rows=spec.get("init_rows"),
                                offset_rows=spec.get("offset_rows"),
                                rng_salt=spec.get("rng_salt"))
                    for spec in passes)
            vmap_args = (arrays, sample_mask, client_mask, client_ids) + \
                ((carry_slots,) if carry_paged else ()) + \
                ((corrupt_mode,) if chaos_corruption else ()) + \
                ((staleness,) if traffic_staleness else ()) + \
                mega_rows
            parts, tls, nss, stats, stale, carry_rows, sub_norms = \
                jax.vmap(per_client)(*vmap_args)
            privacy_per_client = {k: v for k, v in stats.items()
                                  if k.startswith("privacy_")}
            stats = {k: v for k, v in stats.items()
                     if not k.startswith("privacy_")}

            if defer_screen:
                # shield mode: screening needs the FULL cohort's norms,
                # which spans buckets — ship the per-client stack (the
                # same K x model HBM cost the robust_stack path already
                # pays) replicated to the finalize program; nothing
                # crosses to the host
                pc = {
                    "stack": jax.tree.map(gather_axis,
                                          parts["default"][0]),
                    "w": gather_axis(parts["default"][1]),
                    "tl": gather_axis(tls),
                    "ns": gather_axis(nss),
                    "stats": {k: gather_axis(v) for k, v in stats.items()},
                    "cm": gather_axis(client_mask),
                }
                if wants_cohort:
                    # the finalize's masked screening votes on submitted
                    # norms (the stack itself is masked int32 — no norm
                    # signal there by construction)
                    pc["sub_norm"] = gather_axis(sub_norms)
                return pc, privacy_per_client

            cm_k = client_mask
            local = {"parts": {}}
            for name, (trees, ws) in parts.items():
                w_now = ws * (1.0 - stale)
                w_def = ws * stale
                wsum = lambda w, t: jax.tree.map(
                    lambda g: jnp.tensordot(w, g, axes=[[0], [0]]), t)
                if name in strategy.unit_weight_parts:
                    gsum = jax.tree.map(
                        lambda g: jnp.tensordot(
                            cm_k.astype(g.dtype), g, axes=[[0], [0]]),
                        trees)
                    local["parts"][name] = {
                        "grad_sum": gsum,
                        "weight_sum": jnp.sum(w_now),
                        "grad_sum_def": jax.tree.map(
                            jnp.zeros_like, gsum),
                        "weight_sum_def": jnp.sum(w_def),
                        "weight_sum_raw": jnp.sum(ws),
                    }
                    continue
                local["parts"][name] = {
                    "grad_sum": wsum(w_now, trees),
                    "weight_sum": jnp.sum(w_now),
                    "grad_sum_def": wsum(w_def, trees),
                    "weight_sum_def": jnp.sum(w_def),
                    "weight_sum_raw": jnp.sum(ws),
                }
            local.update({
                "train_loss_sum": jnp.sum(tls),
                "num_samples_sum": jnp.sum(nss),
                "client_count": jnp.sum(cm_k),
                "stats_mean_sum": jnp.sum(stats["mean"] * cm_k),
                "stats_mag_sum": jnp.sum(stats["mag"] * cm_k),
                "stats_var_sum": jnp.sum(stats["var_corrected"] * cm_k),
                "stats_norm_sum": jnp.sum(stats["norm"] * cm_k),
            })
            if self.partition_mode == "shard_map":
                local = jax.lax.psum(local, CLIENTS_AXIS)
            out = (local, privacy_per_client)
            if device_carry:
                out += (jax.tree.map(gather_axis, carry_rows),)
            return out

        def shard_entry(params, strategy_state, arrays, sample_mask,
                        client_mask, client_ids, client_lr, round_idx,
                        leakage_threshold, quant_threshold, rng, *rest):
            rest = list(rest)
            # secure-agg cohort operands: the bucket's ids + sampled
            # mask, REPLICATED (every client derives masks toward the
            # whole bucket, not this shard's slice)
            cohort_ids = rest.pop(0) if wants_cohort else None
            cohort_mask = rest.pop(0) if wants_cohort else None
            # megabatch tape: lane axis shard-blocked like the grids, so
            # each shard's lanes point only at its own grid rows
            ptr = rest.pop(0) if mega else None
            seg = rest.pop(0) if mega else None
            if carry_split:
                tables = rest.pop(0)
                strategy_state = {**strategy_state, **tables}
            slots = rest.pop(0) if carry_paged else None
            if carry_split:
                off = jax.lax.axis_index(CLIENTS_AXIS) * shard_slots
                slots = jnp.where(slots >= 0, slots - off, -1)
            corrupt = rest.pop(0) if chaos_corruption else None
            stale = rest.pop(0) if traffic_staleness else None
            pool_arg = rest.pop(0) if pool_mode else None
            return shard_body(params, strategy_state, arrays, sample_mask,
                              client_mask, client_ids, client_lr,
                              round_idx, leakage_threshold,
                              quant_threshold, rng,
                              cohort_ids=cohort_ids,
                              cohort_mask=cohort_mask, carry_slots=slots,
                              corrupt_mode=corrupt, staleness=stale,
                              pool=pool_arg, ptr=ptr, seg=seg)

        if self.partition_mode == "shard_map":
            out_specs = ((rspec, cspec) if defer_screen else
                         (rspec, cspec) +
                         ((rspec,) if device_carry else ()))
            sharded = shard_map(
                shard_entry, mesh=mesh,
                in_specs=(rspec, rspec, cspec, cspec, cspec, cspec, rspec,
                          rspec, rspec, rspec, rspec) +
                         ((rspec, rspec) if wants_cohort else ()) +
                         ((cspec, cspec) if mega else ()) +
                         ((cspec,) if carry_split else ()) +
                         ((cspec,) if carry_paged else ()) +
                         ((cspec,) if chaos_corruption else ()) +
                         ((cspec,) if traffic_staleness else ()) +
                         ((rspec,) if pool_mode else ()),
                out_specs=out_specs, check_vma=False)
        else:
            sharded = shard_entry

        def collect_core(params, strategy_state, arrays, sample_mask,
                         client_mask, client_ids, client_lr, round_idx,
                         leakage_threshold, quant_threshold, rng,
                         *extra_args):
            # chaos fold: identical semantics to the fused round —
            # dropout multiplies into client_mask, straggling truncates
            # the step grid, corruption modes gate on the live mask;
            # the per-bucket counters sum additively in finalize
            chaos_stats = {}
            # the bucket's SAMPLED mask, pre-chaos: secure-agg clients
            # mask toward it; finalize cancels toward the lost slots
            sampled_cm = client_mask
            tape_args = ()
            if mega:
                tape_args = tuple(extra_args[:2])
                extra_args = extra_args[2:]
            n_used = 0
            if carry_paged:
                carry_slots = extra_args[0]
                n_used = 1
            else:
                carry_slots = client_ids
            if chaos_faults:
                chaos_drop, chaos_keep = \
                    extra_args[n_used], extra_args[n_used + 1]
                n_used += 2
                step_live = (jnp.sum(sample_mask, axis=-1) > 0)
                real_steps = jnp.sum(step_live, axis=-1)
                keep_f = (jnp.arange(sample_mask.shape[-2])[None, :]
                          < chaos_keep[:, None]).astype(jnp.float32)
                live_cm = client_mask * (1.0 - chaos_drop)
                chaos_stats = {
                    "chaos_dropped": jnp.sum(client_mask * chaos_drop),
                    "chaos_straggled": jnp.sum(
                        live_cm * (chaos_keep < real_steps)),
                    "chaos_steps_lost": jnp.sum(
                        step_live.astype(jnp.float32) * (1.0 - keep_f)
                        * live_cm[:, None]),
                }
                sample_mask = sample_mask * keep_f[..., None].astype(
                    sample_mask.dtype)
                client_mask = live_cm
            corrupt_args = ()
            if chaos_corruption:
                corrupt_mode = extra_args[n_used]
                n_used += 1
                corrupt_mode = jnp.where(client_mask > 0, corrupt_mode, 0)
                f32 = jnp.float32
                chaos_stats.update({
                    "chaos_nan_injected": jnp.sum(
                        (corrupt_mode == CORRUPT_NAN).astype(f32)),
                    "chaos_scaled": jnp.sum(
                        (corrupt_mode == CORRUPT_SCALE).astype(f32)),
                    "chaos_sign_flipped": jnp.sum(
                        (corrupt_mode == CORRUPT_SIGN_FLIP).astype(f32)),
                })
                corrupt_args = (corrupt_mode,)
            stale_args = ()
            if traffic_staleness:
                stale_vec = extra_args[n_used]
                n_used += 1
                stale_vec = jnp.where(client_mask > 0, stale_vec, 0)
                f32 = jnp.float32
                live = (client_mask > 0).astype(f32)
                binned = jnp.minimum(stale_vec, STALE_HIST_BINS - 1)
                chaos_stats.update({
                    f"traffic_stale_{b}": jnp.sum(
                        (binned == b).astype(f32) * live)
                    for b in range(STALE_HIST_BINS)})
                chaos_stats["traffic_stale_sum"] = jnp.sum(
                    stale_vec.astype(f32) * live)
                stale_args = (stale_vec,)
            pool_args = extra_args[n_used:]
            bcast = strategy.broadcast_params(params, strategy_state)
            if carry_split:
                collect_state = {k: v for k, v in strategy_state.items()
                                 if k not in carry_keys}
                carry_tab_args = ({k: strategy_state[k]
                                   for k in carry_keys},)
            else:
                collect_state = strategy_state
                carry_tab_args = ()
            out = sharded(bcast, collect_state, arrays, sample_mask,
                          client_mask, client_ids, client_lr, round_idx,
                          leakage_threshold, quant_threshold, rng,
                          *((client_ids, sampled_cm) if wants_cohort
                            else ()),
                          *tape_args, *carry_tab_args,
                          *((carry_slots,) if carry_paged else ()),
                          *corrupt_args, *stale_args, *pool_args)
            if defer_screen:
                result = {"pc": out[0], "privacy": out[1]}
            else:
                result = {"local": out[0], "privacy": out[1]}
                if device_carry:
                    result["carry"] = out[2]
            result["chaos"] = chaos_stats
            result["ids"] = client_ids
            if wants_cohort:
                # everything the finalize's per-bucket mask cancellation
                # needs: the bucket's sampled and post-chaos live masks
                # (device arrays — no host sync) and the round index the
                # mask keys derive from
                result["sa"] = {"sampled": sampled_cm,
                                "live": client_mask,
                                "round_idx": round_idx}
            if carry_paged:
                # the finalize's apply_carry scatters by pool slot
                result["slots"] = carry_slots
            # trace-time hygiene: a strategy publish during a COLLECT
            # trace would otherwise be drained by the finalize trace as
            # a leaked tracer; bucket collects drop such publishes (the
            # engine's own update_ratio publish lives in finalize)
            self.devbus.drain()
            return result

        self._bucket_collect_core[mega] = collect_core
        return collect_core

    def _bucket_collect_fn(self, K: int, S: int, ax_packer: AxisPacker,
                           stager: ScalarStager,
                           tape_packer: Optional[AxisPacker] = None
                           ) -> Callable:
        """The staged, jitted collect program for one (K_b, S_b) grid —
        cached per geometry + packer signature.  Entry-point name keys
        on S only: the S set is config-bounded, so a NEW compiled
        variant under one name is exactly the K-quantization churn the
        recompile sentinel should see.  ``tape_packer`` (the megabatch
        ptr/seg tape's own AxisPacker — its lead dim is lanes, not
        clients, so it cannot ride the grid packer) selects the
        megabatch collect core under its own ``megabatch_collect_s{S}``
        entry name — the gate's second arm."""
        mega = tape_packer is not None
        key = (K, S, ax_packer.signature, stager.signature,
               tape_packer.signature if mega else None)
        fn = self._bucket_collect_cache.get(key)
        if fn is not None:
            return fn
        core = self._get_bucket_collect_core(mega=mega)

        carry_paged = self.carry_paged

        def staged(params, strategy_state, ax_bufs, sc_bufs, rng,
                   *rest):
            if mega:
                tp = tape_packer.unpack(rest[0])
                tape = (tp["ptr"], tp["seg"])
                pool_args = rest[1:]
            else:
                tape = ()
                pool_args = rest
            ax = ax_packer.unpack(ax_bufs)
            sc = stager.unpack(sc_bufs)
            carry = (ax["carry_slots"],) if carry_paged else ()
            chaos = ax.get("chaos", ())
            return core(params, strategy_state, ax["arrays"],
                        ax["sample_mask"], ax["client_mask"],
                        ax["client_ids"], sc["client_lr"],
                        sc["round_idx"], sc["leakage"], sc["quant"],
                        rng, *tape, *carry, *chaos, *pool_args)

        name = (f"megabatch_collect_s{S}" if mega
                else f"bucket_collect_s{S}")
        fn = self._instrument(name, jax.jit(staged))
        self._bucket_collect_cache[key] = fn
        self.bucket_shapes_seen.add((K, S))
        return fn

    def _get_bucket_finalize(self) -> Callable:
        """The jitted finalize program: per-bucket partials -> screened/
        combined aggregate -> server step -> ONE packed stats buffer per
        dtype group.  Shapes vary with the round's bucket signature; the
        jit cache (and the sentinel, when on) tracks the variants."""
        if self._bucket_finalize is not None:
            return self._bucket_finalize
        strategy = self.strategy
        shield = self.shield
        robust_stack = shield is not None and shield.wants_stack
        device_carry = self.device_carry
        stale_prob = self.stale_prob
        server_tx = self.server_tx
        wants_cohort = bool(getattr(strategy, "wants_cohort", False))
        min_surv = int(getattr(strategy, "min_survivors", 0) or 0) \
            if wants_cohort else 0

        def cancel_buckets(gsum, outs, survivors_per_bucket):
            """Per-bucket secure-agg mask recovery over the FOLDED sum:
            residuals are additive across buckets (each bucket has its
            own mask graph), so chaining ``cancel_masks`` per bucket
            subtracts exactly the union of (survivor, lost) edge masks.
            Returns the cancelled sum + per-cause recovery counters."""
            f32 = jnp.float32
            rec_drop = jnp.zeros((), f32)
            rec_quar = jnp.zeros((), f32)
            surv_tot = jnp.zeros((), f32)
            for o, surv_b in zip(outs, survivors_per_bucket):
                sa = o["sa"]
                gsum = strategy.cancel_masks(
                    gsum, o["ids"], sa["sampled"], surv_b,
                    sa["round_idx"])
                rec_drop += jnp.sum(
                    ((sa["sampled"] > 0) & (sa["live"] <= 0)).astype(f32))
                rec_quar += jnp.sum(
                    ((sa["live"] > 0) & (surv_b <= 0)).astype(f32))
                surv_tot += jnp.sum((surv_b > 0).astype(f32))
            sa_stats = {"secagg_recovered_dropout": rec_drop,
                        "secagg_recovered_quarantine": rec_quar}
            if min_surv > 0:
                abort = surv_tot < jnp.asarray(min_surv, f32)
                gsum = jax.tree.map(
                    lambda g: g * (1 - abort.astype(g.dtype)), gsum)
                sa_stats["secagg_abort"] = abort.astype(jnp.float32)
            return gsum, sa_stats

        def finalize(params, opt_state, strategy_state, outs, server_lr,
                     rng):
            bcast = strategy.broadcast_params(params, strategy_state)
            shield_counts = None
            sa_stats = {}
            if shield is None:
                # deterministic on-device aggregation order: partial
                # sums fold left-to-right in ascending-bucket order
                total = outs[0]["local"]
                for o in outs[1:]:
                    total = jax.tree.map(jnp.add, total, o["local"])
                part_sums = total["parts"]
                if wants_cohort:
                    # no shield: a bucket's survivors are its post-chaos
                    # live clients
                    default = dict(part_sums["default"])
                    gsum, sa_stats = cancel_buckets(
                        default["grad_sum"], outs,
                        [o["sa"]["live"] for o in outs])
                    default["grad_sum"] = gsum
                    part_sums = dict(part_sums)
                    part_sums["default"] = default
                    total = dict(total)
                    total["parts"] = part_sums
                deferred = None
                if stale_prob > 0.0:
                    default = part_sums["default"]
                    deferred = {"grad_sum": default["grad_sum_def"],
                                "weight_sum": default["weight_sum_def"]}
                agg, new_strategy_state = strategy.combine_parts(
                    part_sums, deferred, strategy_state,
                    jax.random.fold_in(rng, 17),
                    num_clients=total["client_count"],
                    global_params=bcast)
                collected = total
            else:
                # shield mode: assemble the cohort stack (ascending-
                # bucket concatenation), screen against the WHOLE
                # cohort's median norm, zero quarantined clients via
                # jnp.where, then sum/combine — the fused round's
                # screening semantics over the multi-grid cohort
                def cat(*xs):
                    return jnp.concatenate(xs, axis=0)
                stack = jax.tree.map(cat, *[o["pc"]["stack"]
                                            for o in outs])
                w = cat(*[o["pc"]["w"] for o in outs])
                tls = cat(*[o["pc"]["tl"] for o in outs])
                nss = cat(*[o["pc"]["ns"] for o in outs])
                cm = cat(*[o["pc"]["cm"] for o in outs])
                stats = jax.tree.map(cat, *[o["pc"]["stats"]
                                            for o in outs])
                if wants_cohort:
                    # masked stacks carry no plaintext norm signal —
                    # vote on the cat'd submitted norms instead
                    sub_norms = cat(*[o["pc"]["sub_norm"] for o in outs])
                    keep, q_nonfinite, q_norm = shield.screen_masked(
                        sub_norms, tls, w, cm, lambda x: x)
                else:
                    keep, q_nonfinite, q_norm = shield.screen(
                        stack, tls, w, cm, lambda x: x)
                keep_b = keep > 0
                stack = jax.tree.map(
                    lambda g: jnp.where(
                        keep_b.reshape((-1,) + (1,) * (g.ndim - 1)),
                        g, jnp.zeros_like(g)), stack)
                w = jnp.where(keep_b, w, 0.0)
                tls = jnp.where(keep_b, tls, 0.0)
                nss = jnp.where(keep_b, nss, 0.0)
                stats = {k: jnp.where(keep_b, v, 0.0)
                         for k, v in stats.items()}
                cm = cm * keep
                if wants_cohort:
                    # masked payloads sum with coefficient EXACTLY 1 per
                    # surviving slot, in the tree's own int32 dtype (the
                    # fused round's unit-weight discipline — a float
                    # weight would break mask cancellation), then the
                    # per-bucket residual masks toward quarantined and
                    # dropped slots cancel out of the folded sum
                    gsum = jax.tree.map(
                        lambda g: jnp.tensordot(
                            cm.astype(g.dtype), g, axes=[[0], [0]]),
                        stack)
                    sizes = [o["pc"]["cm"].shape[0] for o in outs]
                    surv_buckets = []
                    off = 0
                    for sz in sizes:
                        surv_buckets.append(cm[off:off + sz])
                        off += sz
                    gsum, sa_stats = cancel_buckets(gsum, outs,
                                                    surv_buckets)
                else:
                    gsum = jax.tree.map(
                        lambda g: jnp.tensordot(w, g, axes=[[0], [0]]),
                        stack)
                part_sums = {"default": {
                    "grad_sum": gsum,
                    "weight_sum": jnp.sum(w),
                    "grad_sum_def": jax.tree.map(jnp.zeros_like, gsum),
                    "weight_sum_def": jnp.zeros(()),
                    "weight_sum_raw": jnp.sum(w),
                }}
                collected = {
                    "train_loss_sum": jnp.sum(tls),
                    "num_samples_sum": jnp.sum(nss),
                    "client_count": jnp.sum(cm),
                    "stats_mean_sum": jnp.sum(stats["mean"] * cm),
                    "stats_mag_sum": jnp.sum(stats["mag"] * cm),
                    "stats_var_sum": jnp.sum(
                        stats["var_corrected"] * cm),
                    "stats_norm_sum": jnp.sum(stats["norm"] * cm),
                }
                if robust_stack:
                    agg = strategy.combine_stack(
                        stack, cm, jax.random.fold_in(rng, 17))
                    new_strategy_state = strategy_state
                else:
                    agg, new_strategy_state = strategy.combine_parts(
                        part_sums, None, strategy_state,
                        jax.random.fold_in(rng, 17),
                        num_clients=collected["client_count"],
                        global_params=bcast)
                shield_counts = (jnp.sum(q_nonfinite), jnp.sum(q_norm))
            if device_carry:
                # per-bucket scatters commute (a client id appears in
                # exactly one bucket), so sequential application equals
                # the monolithic single scatter; under fleet paging the
                # scatter index is the pool slot the pager assigned
                for b, o in enumerate(outs):
                    new_strategy_state = strategy.apply_carry(
                        new_strategy_state,
                        o["slots"] if "slots" in o else o["ids"],
                        o["carry"],
                        rng=jax.random.fold_in(
                            jax.random.fold_in(rng, 31), b))
            if self.server_max_grad_norm is not None:
                agg = _clip_by_global_norm(
                    agg, float(self.server_max_grad_norm))
            if strategy.owns_server_update:
                new_params, new_strategy_state = \
                    strategy.apply_server_update(params, agg,
                                                 new_strategy_state,
                                                 server_lr)
                new_opt_state = opt_state
            else:
                opt_state.hyperparams["learning_rate"] = server_lr
                updates, new_opt_state = server_tx.update(
                    agg, opt_state, params)
                new_params = optax.apply_updates(params, updates)
            default_part = part_sums.get("default") or \
                next(iter(part_sums.values()))
            round_stats = {
                "train_loss_sum": collected["train_loss_sum"],
                "num_samples_sum": collected["num_samples_sum"],
                "client_count": collected["client_count"],
                "weight_sum": default_part["weight_sum"],
                "weight_sum_raw": default_part["weight_sum_raw"],
                "grad_mean": collected["stats_mean_sum"]
                / jnp.maximum(collected["client_count"], 1.0),
                "grad_mag": collected["stats_mag_sum"]
                / jnp.maximum(collected["client_count"], 1.0),
                "grad_var": collected["stats_var_sum"]
                / jnp.maximum(collected["client_count"], 1.0),
                "grad_norm": collected["stats_norm_sum"]
                / jnp.maximum(collected["client_count"], 1.0),
                "agg_grad_norm": optax.global_norm(agg),
            }
            chaos_tot = outs[0]["chaos"]
            for o in outs[1:]:
                chaos_tot = jax.tree.map(jnp.add, chaos_tot, o["chaos"])
            round_stats.update(chaos_tot)
            round_stats.update(sa_stats)
            if shield_counts is not None:
                round_stats["shield_nonfinite"] = shield_counts[0]
                round_stats["shield_norm_outlier"] = shield_counts[1]
            privacy = jax.tree.map(
                lambda *xs: jnp.concatenate(xs, axis=0),
                *[o["privacy"] for o in outs])
            for k, v in privacy.items():
                round_stats[k] = v
            if self.devbus.enabled:
                applied = jax.tree.map(lambda a, b: a - b,
                                       new_params, params)
                self.devbus.publish(
                    "update_ratio",
                    optax.global_norm(applied)
                    / (optax.global_norm(new_params) + 1e-12))
                round_stats.update(self.devbus.drain())
            packer = FlatPacker(round_stats)
            k_tot = sum(int(o["ids"].shape[0]) for o in outs)
            # flint: disable=jit-purity trace-time slot-table recording is the flatpack contract (one write per compile, host-side reads only)
            self._stats_packers[("bucketed", k_tot)] = packer
            return (new_params, new_opt_state, new_strategy_state,
                    packer.pack(round_stats))

        # donate only the server state (params/opt/strategy) — the
        # per-bucket partials (arg 3) mostly feed reductions XLA cannot
        # alias in place, and an unusable donation warns per compile
        self._bucket_finalize = self._instrument(
            "bucket_finalize",
            jax.jit(finalize, donate_argnums=(0, 1, 2)))
        return self._bucket_finalize

    def dispatch_bucketed_rounds(self, state: ServerState,
                                 rounds_buckets: list,
                                 client_lrs: list, server_lrs: list,
                                 rng: jax.Array,
                                 leakage_threshold: Optional[float] = None,
                                 quant_thresholds: Optional[list] = None,
                                 chaos_vecs: Optional[list] = None
                                 ) -> Tuple[ServerState, BucketedStats]:
        """Dispatch ``len(rounds_buckets)`` bucketed rounds WITHOUT
        blocking.  ``rounds_buckets[r]`` is round r's list of per-bucket
        :class:`~msrflute_tpu.data.batching.RoundBatch` grids (ascending
        bucket order); ``chaos_vecs[r][b]`` the matching per-bucket
        fault-vector entries.  Per round: one staged collect dispatch
        per occupied bucket, then one finalize dispatch producing the
        round's single packed-stats handle — everything device-side, so
        the pipeline ring and strict-transfer contracts hold unchanged."""
        R = len(rounds_buckets)
        # same stream derivation as the monolithic dispatch (split is a
        # pure function), so a bucketed round sees the exact round rng
        # the monolithic program would have — per-client bit-identity
        rngs = [rng] if R == 1 else list(jax.random.split(rng, R))
        finalize = self._get_bucket_finalize()
        per_round: list = []
        cur = state
        puts = staged_bytes = 0
        lr_dt, rd_dt = np.float32, np.int32
        for r, buckets in enumerate(rounds_buckets):
            outs = []
            round_flops = 0.0
            round_hbm = 0
            for b, batch in enumerate(buckets):
                arrays_host, pool_args = self._host_arrays([batch])
                axis_tree = {
                    "arrays": arrays_host,
                    "sample_mask": batch.sample_mask,
                    "client_mask": batch.client_mask,
                    "client_ids": batch.client_ids,
                }
                if self.carry_paged:
                    axis_tree["carry_slots"] = self._batch_slots(batch)
                entry = (chaos_vecs[r][b] if chaos_vecs is not None
                         else None)
                chaos_host = self._chaos_host(
                    [entry] if entry is not None else None,
                    stacked=False)
                if chaos_host:
                    axis_tree["chaos"] = tuple(chaos_host)
                sc_tree = {
                    "client_lr": lr_dt(client_lrs[r]),
                    "round_idx": rd_dt(cur.round),
                    "leakage": lr_dt(leakage_threshold
                                     if leakage_threshold is not None
                                     else np.inf),
                    "quant": lr_dt(quant_thresholds[r]
                                   if quant_thresholds is not None
                                   else -1.0),
                }
                ax_packer = AxisPacker(axis_tree, lead_ndim=1)
                stager = ScalarStager(sc_tree)
                K, S = (int(batch.sample_mask.shape[0]),
                        int(batch.sample_mask.shape[1]))
                ax_bufs = ax_packer.pack_np(axis_tree)
                sc_bufs = stager.pack_np(sc_tree)
                # flint: disable=put-loop one staged put per dtype group per BUCKET PROGRAM (each loop iteration dispatches its own compiled grid; the leaves are already flatpacked)
                ax_dev = jax.device_put(ax_bufs, self._client_sharding)
                # flint: disable=put-loop same — the scalar group's single staged buffer for this bucket's dispatch
                sc_dev = jax.device_put(sc_bufs, self._replicated)
                puts += len(ax_bufs) + len(sc_bufs)
                staged_bytes += int(
                    sum(bf.nbytes for bf in ax_bufs.values()) +
                    sum(bf.nbytes for bf in sc_bufs.values()))
                # megabatch dispatch gate: when the server attached a
                # super-batch tape, pick megabatch vs per-client vmap
                # PER BUCKET — cached per (K, S) geometry, priced on
                # the compiled cost model at first sight (both arms
                # run once; the verdict is deterministic because cost
                # analyses are static)
                tape = getattr(batch, "mega", None)
                fn_mega = tp_dev = None
                if tape is not None and self.megabatch:
                    tape_tree = {"ptr": tape.ptr, "seg": tape.seg}
                    tape_packer = AxisPacker(tape_tree, lead_ndim=1)
                    fn_mega = self._bucket_collect_fn(
                        K, S, ax_packer, stager, tape_packer=tape_packer)
                    tp_bufs = tape_packer.pack_np(tape_tree)
                    # flint: disable=put-loop the tape's single int32 staged buffer for this bucket's dispatch
                    tp_dev = jax.device_put(tp_bufs, self._client_sharding)
                    puts += len(tp_bufs)
                    staged_bytes += int(sum(bf.nbytes
                                            for bf in tp_bufs.values()))
                fn = self._bucket_collect_fn(K, S, ax_packer, stager)
                arm = (self._mega_gate.get((K, S))
                       if fn_mega is not None else "vmap")
                out = None
                if fn_mega is not None and arm is None and \
                        self.megabatch_autotune and self.xla is not None:
                    out_v = fn(cur.params, cur.strategy_state, ax_dev,
                               sc_dev, rngs[r], *pool_args)
                    self._note_compiles(f"bucket_collect_s{S}", fn)
                    cost_v = dict(self.xla.last_dispatch or {})
                    out_m = fn_mega(cur.params, cur.strategy_state,
                                    ax_dev, sc_dev, rngs[r], tp_dev,
                                    *pool_args)
                    self._note_compiles(f"megabatch_collect_s{S}",
                                        fn_mega)
                    cost_m = dict(self.xla.last_dispatch or {})
                    secs_v = self._roofline_secs(cost_v)
                    secs_m = self._roofline_secs(cost_m)
                    if secs_m <= secs_v:
                        arm, out = "mega", out_m
                    else:
                        arm, out = "vmap", out_v
                        self.push_megabatch_event({
                            "kind": "megabatch_fallback",
                            "reason": "aot_cost",
                            "clients": K, "steps": S,
                            "lanes": int(tape.lanes),
                            "depth": int(tape.depth),
                            "mega_secs_est": secs_m,
                            "vmap_secs_est": secs_v,
                        })
                    self._mega_gate[(K, S)] = arm
                    # the live-MFU snapshot must describe the CHOSEN arm
                    self.xla.last_dispatch = (cost_v if arm == "vmap"
                                              else cost_m)
                elif fn_mega is not None and arm is None:
                    # no compiled cost model in reach (telemetry.xla off
                    # or autotune disabled): the server's analytic slots
                    # precheck already priced the tape — trust it
                    arm = "mega"
                    self._mega_gate[(K, S)] = arm
                if out is None:
                    if arm == "mega":
                        out = fn_mega(cur.params, cur.strategy_state,
                                      ax_dev, sc_dev, rngs[r], tp_dev,
                                      *pool_args)
                        self._note_compiles(f"megabatch_collect_s{S}",
                                            fn_mega)
                    else:
                        out = fn(cur.params, cur.strategy_state, ax_dev,
                                 sc_dev, rngs[r], *pool_args)
                        self._note_compiles(f"bucket_collect_s{S}", fn)
                if self.xla is not None and \
                        self.xla.last_dispatch is not None:
                    round_flops += float(
                        self.xla.last_dispatch.get("flops") or 0.0)
                    round_hbm = max(round_hbm, int(
                        self.xla.last_dispatch.get("hbm_bytes") or 0))
                outs.append(out)
            params, opt_state, strategy_state, vecs = finalize(
                cur.params, cur.opt_state, cur.strategy_state,
                tuple(outs), jnp.asarray(server_lrs[r], jnp.float32),
                rngs[r])
            self._note_compiles("bucket_finalize", finalize)
            if self.xla is not None and \
                    self.xla.last_dispatch is not None:
                round_flops += float(
                    self.xla.last_dispatch.get("flops") or 0.0)
                round_hbm = max(round_hbm, int(
                    self.xla.last_dispatch.get("hbm_bytes") or 0))
                # the live-MFU snapshot must describe the WHOLE bucketed
                # round (collects + finalize), not just whichever
                # program dispatched last
                self.xla.last_dispatch = {
                    "entry": "bucketed_round", "rounds": 1,
                    "flops": round_flops or None,
                    "bytes_accessed": None,
                    "hbm_bytes": round_hbm or None,
                }
            cur = ServerState(params, opt_state, strategy_state,
                              cur.round + 1)
            k_tot = sum(int(batch.sample_mask.shape[0])
                        for batch in buckets)
            packer = self._stats_packers[("bucketed", k_tot)]
            per_round.append(PackedStats(vecs, packer, rounds=1,
                                         stacked=False))
        from ..data.batching import ceil_div
        self.last_dispatch_puts = ceil_div(puts, R)
        self.last_staged_bytes = int(staged_bytes // R)
        return cur, BucketedStats(per_round)

    def run_rounds(self, state: ServerState, batches: list,
                   client_lrs: list, server_lrs: list,
                   rng: jax.Array,
                   leakage_threshold: Optional[float] = None,
                   quant_thresholds: Optional[list] = None,
                   chaos_vecs: Optional[list] = None
                   ) -> Tuple[ServerState, Dict[str, np.ndarray]]:
        """Run ``len(batches)`` rounds in ONE device program (scan) and
        fetch the stats (one transfer per dtype group).

        Returns per-round stats stacked on a leading axis.
        """
        new_state, packed = self.dispatch_rounds(
            state, batches, client_lrs, server_lrs, rng,
            leakage_threshold=leakage_threshold,
            quant_thresholds=quant_thresholds, chaos_vecs=chaos_vecs)
        return new_state, packed.fetch()
