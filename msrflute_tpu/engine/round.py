"""The federated round as one jitted SPMD program.

Parity target: the whole middle of the reference stack —
``federated.Server.dispatch_clients/process_clients``
(``core/federated.py:281-424``), the Worker recv loop
(``core/federated.py:482-632``), and the server-side aggregation half of
``OptimizationServer.train`` (``core/server.py:337-427``).

TPU-native redesign (SURVEY.md §5.8): no message protocol, no work queue.
One compiled ``round_step``:

    shard_map over mesh 'clients' axis:
        vmap(client_update) over the shard's clients        # local SGD
        per-client strategy weight + payload transform      # DP/quant/freeze
        weighted local sums -> psum over 'clients'          # "collection"
    strategy.combine (+ staleness buffer, global DP)        # aggregation
    server optax step on the aggregate pseudo-gradient      # ModelUpdater

The per-round model "broadcast" (reference ``core/federated.py:330-335``,
K-1 unicasts) is just the replicated ``params`` operand — XLA keeps it
resident on every chip; the "harvest" poll loop (``core/federated.py:216-229``)
is a single ``psum`` riding ICI.  Greedy work-stealing is replaced by static
client sharding; imbalance is absorbed by masked padding, which costs FLOPs
on padded samples instead of latency on stragglers — the right trade on MXUs.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..config import FLUTEConfig
from ..data.batching import RoundBatch
from ..models.base import BaseTask
from ..optim import make_optimizer
from ..parallel.mesh import CLIENTS_AXIS, MODEL_AXIS, make_mesh
from ..strategies.base import BaseStrategy
from .client_update import ClientHParams, build_client_update, _clip_by_global_norm


@dataclass
class ServerState:
    """Replicated server-side state threaded through rounds
    (the analogue of the reference's global model + ModelUpdater optimizer +
    strategy buffers)."""

    params: Any
    opt_state: Any
    strategy_state: Any
    round: int = 0


class RoundEngine:
    """Compiles and runs the per-round SPMD program."""

    def __init__(self, task: BaseTask, config: FLUTEConfig,
                 strategy: BaseStrategy, mesh: Optional[Mesh] = None):
        self.task = task
        self.config = config
        self.strategy = strategy
        self.mesh = mesh if mesh is not None else make_mesh()

        cc = config.client_config
        sc = config.server_config
        freeze = cc.get("freeze_layer") or []
        if isinstance(freeze, str):
            freeze = [freeze]
        self.hparams = ClientHParams(
            max_grad_norm=cc.get("max_grad_norm"),
            fedprox_mu=float(cc.get("fedprox_mu", 0.0) or 0.0),
            num_epochs=int(cc.get("num_epochs", 1) or 1),
            freeze_layers=tuple(freeze),
        )
        self.client_update = build_client_update(
            task, cc.optimizer_config, self.hparams)
        self.server_tx = make_optimizer(sc.optimizer_config)
        self.server_max_grad_norm = sc.get("max_grad_norm")
        self.stale_prob = float(getattr(strategy, "stale_prob", 0.0) or 0.0)

        self._client_sharding = NamedSharding(self.mesh, P(CLIENTS_AXIS))
        self._replicated = NamedSharding(self.mesh, P())
        self._round_step = self._build_round_step()

    # ------------------------------------------------------------------
    def init_state(self, rng: jax.Array, params: Any = None) -> ServerState:
        if params is None:
            params = self.task.init_params(rng)
        params = jax.device_put(params, self._replicated)
        opt_state = jax.jit(self.server_tx.init,
                            out_shardings=self._replicated)(params)
        return ServerState(
            params=params,
            opt_state=opt_state,
            strategy_state=self.strategy.init_state(params),
            round=0,
        )

    # ------------------------------------------------------------------
    def _build_round_step(self) -> Callable:
        strategy = self.strategy
        client_update = self.client_update
        stale_prob = self.stale_prob
        mesh = self.mesh
        cspec = P(CLIENTS_AXIS)
        rspec = P()

        def shard_body(params, arrays, sample_mask, client_mask, client_ids,
                       client_lr, rng):
            def per_client(arr_c, mask_c, cm_c, cid_c):
                # Deterministic independent stream per (round, client):
                # jax.random.fold_in discipline (SURVEY.md §7 hard parts).
                rng_c = jax.random.fold_in(rng, cid_c)
                pg, tl, ns, stats = client_update(
                    params, arr_c, mask_c, client_lr, rng_c)
                w = strategy.client_weight(
                    num_samples=ns, train_loss=tl, stats=stats,
                    rng=jax.random.fold_in(rng_c, 1))
                pg, w = strategy.transform_payload(
                    pg, w, jax.random.fold_in(rng_c, 2))
                w = w * cm_c
                if stale_prob > 0.0:
                    coin = jax.random.bernoulli(
                        jax.random.fold_in(rng_c, 3), stale_prob)
                    stale = coin.astype(jnp.float32) * cm_c
                else:
                    stale = jnp.zeros(())
                return pg, w, tl * cm_c, ns * cm_c, stats, stale

            pgs, ws, tls, nss, stats, stale = jax.vmap(per_client)(
                arrays, sample_mask, client_mask, client_ids)

            w_now = ws * (1.0 - stale)
            w_def = ws * stale
            wsum = lambda w: jax.tree.map(
                lambda g: jnp.tensordot(w, g, axes=[[0], [0]]), pgs)
            local = {
                "grad_sum_now": wsum(w_now),
                "weight_sum_now": jnp.sum(w_now),
                "grad_sum_def": wsum(w_def),
                "weight_sum_def": jnp.sum(w_def),
                "train_loss_sum": jnp.sum(tls),
                "num_samples_sum": jnp.sum(nss),
                "client_count": jnp.sum(client_mask),
                "stats_mean_sum": jnp.sum(stats["mean"] * client_mask),
                "stats_mag_sum": jnp.sum(stats["mag"] * client_mask),
                "stats_var_sum": jnp.sum(stats["var_corrected"] * client_mask),
                "stats_norm_sum": jnp.sum(stats["norm"] * client_mask),
                "weight_sum_raw": jnp.sum(ws),
            }
            # the "harvest": one collective instead of K P2P recvs
            return jax.lax.psum(local, CLIENTS_AXIS)

        sharded_collect = shard_map(
            shard_body, mesh=mesh,
            in_specs=(rspec, cspec, cspec, cspec, cspec, rspec, rspec),
            out_specs=rspec, check_vma=False)

        def round_step(params, opt_state, strategy_state, arrays, sample_mask,
                       client_mask, client_ids, client_lr, server_lr, rng):
            collected = sharded_collect(
                params, arrays, sample_mask, client_mask, client_ids,
                client_lr, rng)
            deferred = None
            if stale_prob > 0.0:
                deferred = {"grad_sum": collected["grad_sum_def"],
                            "weight_sum": collected["weight_sum_def"]}
            agg, new_strategy_state = strategy.combine(
                collected["grad_sum_now"], collected["weight_sum_now"],
                deferred, strategy_state, jax.random.fold_in(rng, 17),
                num_clients=collected["client_count"])
            # server optimizer over the aggregate pseudo-gradient
            # (reference ModelUpdater.update_model, core/trainer.py:127-137)
            if self.server_max_grad_norm is not None:
                agg = _clip_by_global_norm(agg, float(self.server_max_grad_norm))
            opt_state.hyperparams["learning_rate"] = server_lr
            updates, new_opt_state = self.server_tx.update(agg, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            round_stats = {
                "train_loss_sum": collected["train_loss_sum"],
                "num_samples_sum": collected["num_samples_sum"],
                "client_count": collected["client_count"],
                "weight_sum": collected["weight_sum_now"],
                "weight_sum_raw": collected["weight_sum_raw"],
                "grad_mean": collected["stats_mean_sum"] / jnp.maximum(collected["client_count"], 1.0),
                "grad_mag": collected["stats_mag_sum"] / jnp.maximum(collected["client_count"], 1.0),
                "grad_var": collected["stats_var_sum"] / jnp.maximum(collected["client_count"], 1.0),
                "grad_norm": collected["stats_norm_sum"] / jnp.maximum(collected["client_count"], 1.0),
                "agg_grad_norm": optax.global_norm(agg),
            }
            return new_params, new_opt_state, new_strategy_state, round_stats

        return jax.jit(round_step, donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------
    def run_round(self, state: ServerState, batch: RoundBatch,
                  client_lr: float, server_lr: float,
                  rng: jax.Array) -> Tuple[ServerState, Dict[str, float]]:
        """Stage one round's data onto the mesh and execute the program."""
        arrays = {k: jax.device_put(v, self._client_sharding)
                  for k, v in batch.arrays.items()}
        sample_mask = jax.device_put(batch.sample_mask, self._client_sharding)
        client_mask = jax.device_put(batch.client_mask, self._client_sharding)
        client_ids = jax.device_put(batch.client_ids, self._client_sharding)

        params, opt_state, strategy_state, stats = self._round_step(
            state.params, state.opt_state, state.strategy_state,
            arrays, sample_mask, client_mask, client_ids,
            jnp.asarray(client_lr, jnp.float32),
            jnp.asarray(server_lr, jnp.float32), rng)
        new_state = ServerState(params, opt_state, strategy_state,
                                state.round + 1)
        return new_state, stats
