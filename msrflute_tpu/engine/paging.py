"""Fleet paged carry tables (``server_config.fleet``) — O(cache) HBM.

The PR 6 carry design keeps each device-carry strategy's per-client
state (SCAFFOLD controls, EF residuals, personalization heads/alphas)
as ``[N, n_params]`` device residents inside ``strategy_state``.  That
is exactly the thing that cannot scale to 10^6 clients: at fleet size
the tables, not the model, own HBM.

This module replaces the resident tables with a **fixed-capacity page
pool** plus a **host backing store**, behind the SAME
``client_step_carry`` / ``apply_carry`` gather/scatter hooks:

- the tables shrink to ``[P, ...]`` where ``P = fleet.page_pool_slots``
  (``strategy.carry_rows``); the in-program math is unchanged because
  the engine feeds the carry hooks host-remapped SLOT ids instead of
  client ids (the per-client rng streams keep folding on the TRUE
  client id, so per-client math is bit-identical to resident mode);
- before each chunk dispatches, :meth:`CarryPager.prepare_chunk` maps
  the cohort onto slots: hits reuse their resident row, misses page in
  from the host store as ONE fixed-shape scatter (width pow2-quantized,
  sentinel-padded with out-of-bounds drop — zero post-warmup
  recompiles by construction) that donates the tables in sequence with
  the round programs;
- right after dispatch, :meth:`queue_writeback` dispatches a small
  gather of the chunk's slot rows from the post-chunk tables (reading
  BEFORE the next dispatch donates them — the ``dp_clip`` stash
  discipline); the pipeline drain completes it with one explicit
  ``device_get`` and writes the rows through to the host store, so a
  slot is evictable exactly when no in-flight chunk pins it;
- eviction is LRU over unpinned slots; pinned (in-flight) rows are
  never evicted, so depth-N pipelining stays safe — a pool too small
  for ``(depth+1)`` cohorts refuses loudly instead of corrupting rows;
- durability rides the :class:`FleetRowStore`: RAM-LRU rows with
  crash-safe ``.npz`` spill under the model dir and the same
  round-marker pairing as the SCAFFOLD ``ControlStore`` — a resumed
  run reloads rows from disk into an EMPTY pool (slot numbering is
  invisible to the math), so preempt-and-resume stays bit-identical.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np


def _pow2_width(n: int, floor: int = 8) -> int:
    """Pow2-quantized program width for the page-in/writeback programs:
    the compiled-variant set stays logarithmic and closes after
    warmup."""
    n = max(int(n), int(floor))
    return 1 << max(n - 1, 0).bit_length()


class FleetRowStore:
    """Host backing store for paged carry rows.

    One logical row per client: a dict ``{table_key: np.ndarray}``.
    RAM is LRU-bounded at ``cache_rows``; evicting a dirty row writes
    it through to disk first (crash-safe tmp+rename ``.npz``), so the
    union of RAM and disk is always the current row set.  ``flush()``
    writes the remaining dirty rows through — the server calls it at
    ``fleet.spill_freq`` cadence and commits the round marker only
    after the paired model checkpoint is durable (the ControlStore
    discipline; a marker/checkpoint mismatch on resume resets the
    rows — carry state belongs to exactly one parameter trajectory).
    """

    def __init__(self, store_dir: Optional[str], cache_rows: int = 8192,
                 resume: bool = False):
        self.store_dir = store_dir
        self.cache_rows = max(int(cache_rows), 1)
        self._rows: "OrderedDict[int, Dict[str, np.ndarray]]" = \
            OrderedDict()
        self._dirty: set = set()
        self.spilled_rows = 0
        if store_dir is not None:
            os.makedirs(store_dir, exist_ok=True)
            if not resume:
                self._wipe_files()

    # -- paths ----------------------------------------------------------
    def _path(self, cid: int) -> str:
        return os.path.join(self.store_dir, f"row_{int(cid)}.npz")

    def _marker_path(self) -> str:
        return os.path.join(self.store_dir, "fleet_round.npy")

    def _wipe_files(self) -> None:
        for name in os.listdir(self.store_dir):
            if name.startswith("row_") or name == "fleet_round.npy":
                os.remove(os.path.join(self.store_dir, name))

    # -- rows -----------------------------------------------------------
    def get(self, cid: int) -> Optional[Dict[str, np.ndarray]]:
        cid = int(cid)
        row = self._rows.get(cid)
        if row is not None:
            self._rows.move_to_end(cid)
            return row
        if self.store_dir is not None:
            path = self._path(cid)
            if os.path.exists(path):
                with np.load(path) as zf:
                    row = {k: zf[k] for k in zf.files}
                self._insert(cid, row, dirty=False)
                return row
        return None

    def put(self, cid: int, row: Dict[str, np.ndarray]) -> None:
        self._insert(int(cid), row, dirty=True)

    def _insert(self, cid: int, row: Dict[str, np.ndarray],
                dirty: bool) -> None:
        self._rows.pop(cid, None)
        self._rows[cid] = row
        if dirty:
            self._dirty.add(cid)
        while len(self._rows) > self.cache_rows:
            old_cid, old_row = self._rows.popitem(last=False)
            if old_cid in self._dirty:
                # nowhere else holds the latest value: spill-through
                self._write(old_cid, old_row)
                self._dirty.discard(old_cid)
                self.spilled_rows += 1

    def _write(self, cid: int, row: Dict[str, np.ndarray]) -> None:
        if self.store_dir is None:
            return
        path = self._path(cid)
        tmp = path + ".tmp.npz"  # .npz suffix stops np.savez appending one
        np.savez(tmp, **row)
        os.replace(tmp, path)

    def has_rows(self) -> bool:
        """Whether ANY client has a stored row (RAM or disk) — the
        cheap personalized-eval seen gate.  scandir short-circuits at
        the first row file: O(1), never an O(N)-filename listing."""
        if self._rows:
            return True
        if self.store_dir is None:
            return False
        with os.scandir(self.store_dir) as it:
            return any(entry.name.startswith("row_") for entry in it)

    # -- durability -----------------------------------------------------
    def flush(self) -> int:
        """Write every dirty RAM row through to disk; returns the row
        count (the spill transfer meter)."""
        if self.store_dir is None:
            self._dirty.clear()
            return 0
        n = 0
        for cid in sorted(self._dirty):
            row = self._rows.get(cid)
            if row is not None:
                self._write(cid, row)
                n += 1
        self._dirty.clear()
        return n

    def set_round(self, round_no: int) -> None:
        if self.store_dir is None:
            return
        path = self._marker_path()
        tmp = path + ".tmp.npy"
        np.save(tmp, np.asarray([int(round_no)], np.int64))
        os.replace(tmp, path)

    def round(self) -> Optional[int]:
        if self.store_dir is None or not os.path.exists(
                self._marker_path()):
            return None
        return int(np.load(self._marker_path())[0])

    def reset(self) -> None:
        """Drop every row + marker (trajectory-mismatch semantics)."""
        self._rows.clear()
        self._dirty.clear()
        if self.store_dir is not None:
            self._wipe_files()


class CarryPager:
    """Slot allocator + page-in/writeback programs for ONE run's carry
    tables.  Single-threaded by design: every method is called from the
    server's round loop (prepare -> dispatch -> queue -> drain)."""

    def __init__(self, strategy, state_tables: Dict[str, Any],
                 slots: int, mesh,
                 store_dir: Optional[str] = None,
                 host_cache_rows: int = 8192,
                 resume: bool = False):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.strategy = strategy
        self.keys = tuple(strategy.carry_tables)
        if not self.keys:
            raise ValueError(
                f"{type(strategy).__name__} declares no carry_tables — "
                "fleet paging has nothing to page; drop the fleet block "
                "or use a device-carry strategy")
        self.n_slots = int(slots)
        # per-key row geometry straight off the live tables (shape[0]
        # is the slot count; everything after is the row)
        self._row_shape = {}
        self._row_dtype = {}
        for k in self.keys:
            leaf = state_tables[k]
            if int(leaf.shape[0]) != self.n_slots:
                raise ValueError(
                    f"fleet paging: strategy_state[{k!r}] has "
                    f"{int(leaf.shape[0])} rows but the page pool is "
                    f"{self.n_slots} slots — carry_rows was not applied "
                    "before init_state")
            self._row_shape[k] = tuple(int(d) for d in leaf.shape[1:])
            self._row_dtype[k] = np.dtype(str(leaf.dtype))
        self._defaults = dict(strategy.carry_row_defaults())
        self._rep = NamedSharding(mesh, P())
        self.store = FleetRowStore(store_dir, cache_rows=host_cache_rows,
                                   resume=resume)

        # ---- slot state ----------------------------------------------
        self._free: List[int] = list(range(self.n_slots - 1, -1, -1))
        self._slot_client = np.full((self.n_slots,), -1, np.int64)
        self._client_slot: Dict[int, int] = {}
        self._pins = np.zeros((self.n_slots,), np.int64)
        #: unpinned slots in LRU order (front = evict first)
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._ticket: Optional[Dict[str, Any]] = None

        # ---- compiled program caches (one per pow2 width) ------------
        self._scatter_cache: Dict[int, Any] = {}
        self._gather_cache: Dict[int, Any] = {}
        self._jax = jax

        # ---- counters (bench marker + devbus gauges) -----------------
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.page_in_rows = 0
        self.writeback_rows = 0

    # ------------------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        return {
            "pool_slots": self.n_slots,
            "resident": int(len(self._client_slot)),
            "hits": int(self.hits),
            "misses": int(self.misses),
            "evictions": int(self.evictions),
            "page_in_rows": int(self.page_in_rows),
            "writeback_rows": int(self.writeback_rows),
            "spilled_rows": int(self.store.spilled_rows),
            "tables": list(self.keys),
        }

    def hbm_row_bytes(self) -> int:
        """Bytes one pool row costs across all table keys — the pool's
        HBM budget is ``n_slots * hbm_row_bytes()``, independent of N."""
        return int(sum(
            int(np.prod(self._row_shape[k], dtype=np.int64) or 1)
            * self._row_dtype[k].itemsize for k in self.keys))

    # ------------------------------------------------------------------
    # slot allocation
    # ------------------------------------------------------------------
    def _pin(self, slot: int) -> None:
        if self._pins[slot] == 0:
            self._lru.pop(slot, None)
        self._pins[slot] += 1

    def _unpin(self, slot: int) -> None:
        self._pins[slot] -= 1
        if self._pins[slot] <= 0:
            self._pins[slot] = 0
            if self._slot_client[slot] >= 0:
                self._lru[slot] = None  # tail = most recently used

    def _alloc(self, cid: int) -> int:
        if self._free:
            slot = self._free.pop()
        elif self._lru:
            slot, _ = self._lru.popitem(last=False)  # LRU head
            old = int(self._slot_client[slot])
            # the host store already holds the evictee's current row:
            # unpinned means every chunk that touched it drained, and
            # the drain wrote the row back — eviction costs zero device
            # traffic
            self._client_slot.pop(old, None)
            self.evictions += 1
        else:
            raise ValueError(
                f"fleet.page_pool_slots={self.n_slots} cannot hold the "
                "in-flight cohorts: every slot is pinned by a dispatched "
                "chunk — raise page_pool_slots (it must cover "
                "(pipeline_depth + 1) x cohort x rounds_per_step rows)")
        self._slot_client[slot] = cid
        self._client_slot[cid] = slot
        return slot

    # ------------------------------------------------------------------
    # per-chunk flow
    # ------------------------------------------------------------------
    def prepare_chunk(self, batches: list, strategy_state: Any) -> Any:
        """Map the chunk's cohorts onto pool slots (writes
        ``batch.carry_slots`` on every grid, -1 for padding lanes),
        page missing rows in as one fixed-shape donated scatter, and
        pin the touched slots until this chunk drains.  Returns the
        (possibly updated) ``strategy_state``."""
        if self._ticket is not None:
            raise RuntimeError(
                "fleet pager: prepare_chunk called with an unconsumed "
                "ticket — queue_writeback must run after each dispatch")
        flat = [b for entry in batches
                for b in (entry if isinstance(entry, list) else [entry])]
        chunk_slots: "OrderedDict[int, int]" = OrderedDict()  # slot->cid
        miss: List[tuple] = []
        for b in flat:
            ids = np.asarray(b.client_ids)
            slots = np.full(ids.shape, -1, np.int32)
            for j, cid in enumerate(ids):
                cid = int(cid)
                if cid < 0:
                    continue
                slot = self._client_slot.get(cid)
                if slot is None:
                    slot = self._alloc(cid)
                    miss.append((cid, slot))
                    self.misses += 1
                else:
                    self.hits += 1
                    if self._pins[slot] == 0 and slot in self._lru:
                        self._lru.move_to_end(slot)
                slots[j] = slot
                if slot not in chunk_slots:
                    chunk_slots[slot] = cid
                    self._pin(slot)
            b.carry_slots = slots
        self._ticket = {
            "slots": np.asarray(list(chunk_slots), np.int32),
            "ids": np.asarray(list(chunk_slots.values()), np.int64),
        }
        if miss:
            strategy_state = self._page_in(strategy_state, miss)
        return strategy_state

    def _page_in(self, strategy_state: Any, miss: List[tuple]) -> Any:
        jax = self._jax
        import jax.numpy as jnp
        W = _pow2_width(len(miss))
        slot_arr = np.full((W,), self.n_slots, np.int32)  # sentinel: drop
        rows = {k: np.full((W,) + self._row_shape[k],
                           self._defaults.get(k, 0.0),
                           self._row_dtype[k]) for k in self.keys}
        for i, (cid, slot) in enumerate(miss):
            slot_arr[i] = slot
            stored = self.store.get(cid)
            if stored is not None:
                for k in self.keys:
                    rows[k][i] = stored[k]
        self.page_in_rows += len(miss)
        fn = self._scatter_cache.get(W)
        if fn is None:
            keys = self.keys

            def scatter(tables, slots, new_rows):
                # sentinel-padded lanes target index n_slots: out of
                # bounds, mode="drop" — the fixed [W] shape never
                # retraces on the miss count
                return {k: tables[k].at[slots].set(new_rows[k],
                                                   mode="drop")
                        for k in keys}

            fn = jax.jit(scatter, donate_argnums=(0,))
            self._scatter_cache[W] = fn
        tables = {k: strategy_state[k] for k in self.keys}
        # one replicated put for the whole padded row dict — the page-in
        # transfer is len(keys) buffers regardless of miss count
        rows_dev = jax.device_put(rows, self._rep)
        new_tables = fn(tables, jnp.asarray(slot_arr), rows_dev)
        new_state = dict(strategy_state)
        new_state.update(new_tables)
        return new_state

    def queue_writeback(self, strategy_state: Any) -> Dict[str, Any]:
        """Dispatch the async gather of this chunk's slot rows from the
        POST-chunk tables.  Must run before the next dispatch donates
        ``strategy_state`` (program order then guarantees the gather
        reads the chunk's output).  Returns the handle the drain
        completes."""
        ticket = self._ticket
        self._ticket = None
        if ticket is None or ticket["slots"].size == 0:
            return {"ids": np.empty((0,), np.int64), "rows": None,
                    "slots": np.empty((0,), np.int32)}
        jax = self._jax
        import jax.numpy as jnp
        W = _pow2_width(int(ticket["slots"].size))
        slot_arr = np.zeros((W,), np.int32)
        slot_arr[:ticket["slots"].size] = ticket["slots"]
        fn = self._gather_cache.get(W)
        if fn is None:
            n_slots = self.n_slots
            keys = self.keys

            def gather(tables, slots):
                idx = jnp.clip(slots, 0, n_slots - 1)
                return {k: tables[k][idx] for k in keys}

            fn = jax.jit(gather)
            self._gather_cache[W] = fn
        tables = {k: strategy_state[k] for k in self.keys}
        rows = fn(tables, jnp.asarray(slot_arr))
        return {"ids": ticket["ids"], "slots": ticket["slots"],
                "rows": rows}

    def complete_writeback(self, handle: Dict[str, Any]) -> None:
        """Drain half: ONE explicit fetch of the gathered rows, write
        them through to the host store, unpin the chunk's slots."""
        ids = handle["ids"]
        if handle["rows"] is None or ids.size == 0:
            return
        jax = self._jax
        fetched = jax.device_get(handle["rows"])
        for i, cid in enumerate(ids):
            self.store.put(int(cid),
                           {k: np.asarray(fetched[k][i])
                            for k in self.keys})
        self.writeback_rows += int(ids.size)
        for slot in handle["slots"]:
            self._unpin(int(slot))

    # ------------------------------------------------------------------
    # host-side reads (personalized eval) + durability
    # ------------------------------------------------------------------
    def user_row(self, uid: int) -> Optional[Dict[str, np.ndarray]]:
        """The client's CURRENT carry row from the host store (valid at
        any drained boundary — eval boundaries fully drain the ring),
        or None for a never-participated client."""
        return self.store.get(int(uid))

    def has_rows(self) -> bool:
        return self.store.has_rows()

    def flush(self) -> int:
        return self.store.flush()

    def set_round(self, round_no: int) -> None:
        self.store.set_round(round_no)

    def round(self) -> Optional[int]:
        return self.store.round()

    def reset(self) -> None:
        """Trajectory mismatch on resume: drop the host rows AND the
        slot map — every next touch cold-starts from the defaults,
        exactly like a fresh table."""
        self.store.reset()
        self._free = list(range(self.n_slots - 1, -1, -1))
        self._slot_client[:] = -1
        self._client_slot.clear()
        self._pins[:] = 0
        self._lru.clear()
        self._ticket = None
