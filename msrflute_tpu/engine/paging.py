"""Fleet paged carry tables (``server_config.fleet``) — O(cache) HBM,
mesh-sharded transfer plane.

The PR 6 carry design keeps each device-carry strategy's per-client
state (SCAFFOLD controls, EF residuals, personalization heads/alphas)
as ``[N, n_params]`` device residents inside ``strategy_state``.  That
is exactly the thing that cannot scale to 10^6 clients: at fleet size
the tables, not the model, own HBM.

This module replaces the resident tables with a **fixed-capacity page
pool** plus a **host backing store**, behind the SAME
``client_step_carry`` / ``apply_carry`` gather/scatter hooks:

- the tables shrink to ``[P, ...]`` where ``P = fleet.page_pool_slots``
  (``strategy.carry_rows``); the in-program math is unchanged because
  the engine feeds the carry hooks host-remapped SLOT ids instead of
  client ids (the per-client rng streams keep folding on the TRUE
  client id, so per-client math is bit-identical to resident mode);
- **the pool's slot axis is sharded over the clients mesh axis**
  (``parallel.sharding.slot_pool_sharding``), exactly like the resident
  tables it replaced: slots partition into ``mesh_size`` contiguous
  per-shard blocks, and the allocator is SHARD-AWARE — a lane's client
  gets a slot on the shard that computes the lane
  (``data.fleet.lane_shard_map``), so the in-program gather/scatter by
  ``carry_slots`` is shard-local with no cross-shard collective, and
  pool HBM / page-in bytes / writeback bytes all cost total/mesh_size
  per device instead of xmesh_size;
- before each chunk dispatches, :meth:`CarryPager.prepare_chunk` maps
  the cohort onto slots: hits reuse their resident row, misses page in
  from the host store as ONE fixed-shape SHARDED scatter — per-shard
  segments of a single ``[M*W]`` buffer (width pow2-quantized,
  sentinel-padded with out-of-bounds drop — zero post-warmup
  recompiles by construction) that donates the tables in sequence with
  the round programs; each device receives only its own segment;
- a client resampled onto a DIFFERENT shard migrates: its old slot is
  freed and the row pages in from the host store on the new shard.  If
  the old slot is still pinned by an in-flight chunk, the pager
  force-completes that chunk's already-dispatched writeback gather
  first (one explicit early fetch — the gather's value is the
  post-chunk row, so the host store is current before the migration
  pages it back in);
- right after dispatch, :meth:`queue_writeback` dispatches a small
  per-shard gather of the chunk's slot rows from the post-chunk tables
  (reading BEFORE the next dispatch donates them — the ``dp_clip``
  stash discipline); the pipeline drain completes it with one explicit
  ``device_get`` that fetches the per-shard slices, and writes the
  rows through to the host store, so a slot is evictable exactly when
  no in-flight chunk pins it;
- **prefetch** (``fleet.prefetch``, default on): while round k
  executes, a named ``fleet-prefetch`` worker thread stages round
  k+1's missing rows from the host store into a staging buffer —
  read-only against the store (RAM peek under the store lock, direct
  ``.npz`` read otherwise), so the allocator stays single-threaded and
  the staged values are exactly what the synchronous path would load
  (a prefetch-missing client cannot be resident, hence cannot have a
  pending writeback that would make the staged row stale).  The
  page-in's host IO leaves the critical path; the hit rate is a
  devbus gauge;
- eviction is LRU over unpinned slots PER SHARD; pinned (in-flight)
  rows are never evicted, so depth-N pipelining stays safe — per-shard
  contention drains the oldest outstanding writeback before giving up,
  and a pool too small overall refuses loudly instead of corrupting
  rows;
- durability rides the :class:`FleetRowStore`: RAM-LRU rows with
  crash-safe ``.npz`` spill under the model dir and the same
  round-marker pairing as the SCAFFOLD ``ControlStore`` — a resumed
  run reloads rows from disk into an EMPTY pool (slot numbering is
  invisible to the math), so preempt-and-resume stays bit-identical.
  Rows key by GLOBAL client id, so under multihost each host's shard
  of the page-in never needs another host's rows.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

import numpy as np

from ..data.fleet import lane_shard_map
from ..parallel.mesh import CLIENTS_AXIS, clients_axis_size
from ..telemetry import emit_event


def _pow2_width(n: int, floor: int = 8) -> int:
    """Pow2-quantized program width for the page-in/writeback programs:
    the compiled-variant set stays logarithmic and closes after
    warmup."""
    n = max(int(n), int(floor))
    return 1 << max(n - 1, 0).bit_length()


def read_marker(store_dir: Optional[str]) -> Optional[int]:
    """The durable fleet round marker under ``store_dir`` (None when the
    store has never committed one).  A module function so the server's
    resume-anchor pairing can probe the marker BEFORE deciding which
    checkpoint slot to restore — the :class:`FleetRowStore` itself is
    only built after that decision."""
    if store_dir is None:
        return None
    path = os.path.join(store_dir, "fleet_round.npy")
    if not os.path.exists(path):
        return None
    return int(np.load(path)[0])


def _parse_row_name(name: str) -> Optional[tuple]:
    """``row_{cid}.g{gen}.npz`` -> (cid, gen); legacy ``row_{cid}.npz``
    -> (cid, 0); anything else (tmp files, the marker) -> None."""
    if not name.startswith("row_") or not name.endswith(".npz") \
            or ".tmp" in name:
        return None
    stem = name[len("row_"):-len(".npz")]
    if ".g" in stem:
        cid_s, _, gen_s = stem.partition(".g")
    else:
        cid_s, gen_s = stem, "0"
    try:
        return int(cid_s), int(gen_s)
    except ValueError:
        return None


class FleetRowStore:
    """Host backing store for paged carry rows.

    One logical row per client, keyed by GLOBAL client id: a dict
    ``{table_key: np.ndarray}``.  RAM is LRU-bounded at ``cache_rows``;
    evicting a dirty row writes it through to disk first (crash-safe
    tmp+rename ``.npz``), so the union of RAM and disk is always the
    current row set.  ``flush()`` writes the remaining dirty rows
    through — the server calls it at ``fleet.spill_freq`` cadence and
    commits the round marker only after the paired model checkpoint is
    durable (the ControlStore discipline; a marker behind the resumed
    checkpoint resets the rows — carry state belongs to exactly one
    parameter trajectory).

    Spill files are GENERATION-versioned (flutearmor crash-point
    contract): each row lands at ``row_{cid}.g{round}.npz`` where
    ``round`` is the round whose writeback produced the content
    (``put_round``, set by the pager per writeback), and overwriting a
    row keeps its previous generation on disk until :meth:`mark_durable`
    says a checkpoint at or past that generation is durable.  A hard
    kill at ANY byte of the spill/marker/checkpoint sequence then leaves
    a bit-identical resume reachable: the server resumes from the slot
    matching the marker and :meth:`adopt_round` prunes the dead
    trajectory's newer generations, so every row read yields exactly the
    content it had at the resumed round.

    Mutations happen only on the server's round-loop thread; the
    ``fleet-prefetch`` worker reads through :meth:`peek` (RAM/spilling
    maps under ``_ram_lock``, no LRU mutation) and :meth:`_read_file`
    (atomic-replace ``.npz``, torn-read safe) — dirty evictees sit in
    the ``_spilling`` map until their file write lands, so a
    concurrent peek never sees a row in neither place.
    """

    def __init__(self, store_dir: Optional[str], cache_rows: int = 8192,
                 resume: bool = False, ladder=None):
        self.store_dir = store_dir
        self.cache_rows = max(int(cache_rows), 1)
        #: optional resilience.DurableIOLadder: spill writes and the
        #: round marker retry-then-escalate; reads retry-then-raise
        #: (losing a carry row corrupts training) — None keeps the
        #: historical raw-IO behaviour for direct constructions
        self.ladder = ladder
        self._rows: "OrderedDict[int, Dict[str, np.ndarray]]" = \
            OrderedDict()
        self._dirty: set = set()
        #: dirty evictees between pop-from-RAM and the (outside-lock)
        #: file write — readable by peek() so the row never vanishes
        self._spilling: Dict[int, Dict[str, np.ndarray]] = {}
        self._ram_lock = threading.Lock()
        self.spilled_rows = 0
        #: content round per RAM row (the generation a spill writes to)
        self._tags: Dict[int, int] = {}
        #: known on-disk generations per row, sorted ascending
        self._gens: Dict[int, List[int]] = {}
        #: newest round whose checkpoint is known durable — generations
        #: superseded by a newer one at/below this are garbage
        self._safe_round = -1
        #: round tag for incoming put()s — the pager sets this per
        #: writeback batch; direct constructions default to one
        #: generation (tag 0), the historical single-file behaviour
        self.put_round = 0
        if store_dir is not None:
            os.makedirs(store_dir, exist_ok=True)
            if resume:
                self._scan_gens()
            else:
                self._wipe_files()

    # -- paths ----------------------------------------------------------
    def _path(self, cid: int, gen: int = 0) -> str:
        return os.path.join(self.store_dir,
                            f"row_{int(cid)}.g{int(gen)}.npz")

    def _marker_path(self) -> str:
        return os.path.join(self.store_dir, "fleet_round.npy")

    def _wipe_files(self) -> None:
        for name in os.listdir(self.store_dir):
            if name.startswith("row_") or name == "fleet_round.npy":
                os.remove(os.path.join(self.store_dir, name))

    def _scan_gens(self) -> None:
        """Resume inventory: one directory listing builds the
        per-row generation map the reads select from."""
        gens: Dict[int, List[int]] = {}
        for name in os.listdir(self.store_dir):
            parsed = _parse_row_name(name)
            if parsed is not None:
                gens.setdefault(parsed[0], []).append(parsed[1])
        for lst in gens.values():
            lst.sort()
        with self._ram_lock:
            self._gens = gens

    def _newest_gen(self, cid: int) -> Optional[int]:
        with self._ram_lock:
            gens = self._gens.get(cid)
            return gens[-1] if gens else None

    def adopt_round(self, round_no: int) -> None:
        """Resume adoption: delete every generation NEWER than the
        resumed round — the dead trajectory's future — so every
        subsequent read yields the row exactly as of the anchor."""
        round_no = int(round_no)
        doomed: List[tuple] = []
        with self._ram_lock:
            for cid, gens in list(self._gens.items()):
                for g in [g for g in gens if g > round_no]:
                    gens.remove(g)
                    doomed.append((cid, g))
                if not gens:
                    del self._gens[cid]
        for cid, g in doomed:
            try:
                os.remove(self._path(cid, g))
            except OSError:
                pass

    def mark_durable(self, round_no: int) -> None:
        """A checkpoint at/past ``round_no`` is durable: generations
        superseded at/below it become prunable (GC happens lazily at
        each row's next spill — no directory scans on the hot path)."""
        self._safe_round = max(self._safe_round, int(round_no))

    def _register_gen(self, cid: int, gen: int) -> None:
        """Record a landed spill and GC this row's superseded
        generations: a generation is garbage once a NEWER one exists
        at or below the durable horizon (any future resume anchors at
        or past the horizon, so the newest covered generation is the
        one every reachable anchor selects)."""
        doomed: List[int] = []
        with self._ram_lock:
            gens = self._gens.setdefault(cid, [])
            if gen not in gens:
                gens.append(gen)
                gens.sort()
            covered = [g for g in gens if g <= self._safe_round]
            if covered:
                doomed = [g for g in gens if g < covered[-1]]
                for g in doomed:
                    gens.remove(g)
        for g in doomed:
            try:
                os.remove(self._path(cid, g))
            except OSError:
                pass

    # -- rows -----------------------------------------------------------
    def _read_file(self, cid: int) -> Optional[Dict[str, np.ndarray]]:
        """Stateless disk read (no RAM insert, no LRU motion) — the
        prefetch thread's half of :meth:`get`."""
        if self.store_dir is None:
            return None
        gen = self._newest_gen(cid)
        if gen is None:
            return None
        path = self._path(cid, gen)
        if not os.path.exists(path):
            return None
        with np.load(path) as zf:
            return {k: zf[k] for k in zf.files}

    def peek(self, cid: int) -> Optional[Dict[str, np.ndarray]]:
        """RAM (or in-spill) row WITHOUT LRU mutation — safe from the
        prefetch thread; row dicts are replaced, never mutated in
        place, so the returned mapping is stable."""
        cid = int(cid)
        with self._ram_lock:
            row = self._rows.get(cid)
            if row is None:
                row = self._spilling.get(cid)
        return row

    def _read_durable(self, cid: int) -> Optional[Dict[str, np.ndarray]]:
        """The main-thread disk read: under the ladder, a transient
        error retries with backoff and EXHAUSTION RAISES (DurableIOError
        -> flight-recorded abort) — a silently-lost carry row would
        corrupt training.  The prefetch thread never comes through here;
        its failures degrade to cold paging instead."""
        if self.ladder is None:
            return self._read_file(cid)
        box: Dict[str, Any] = {}

        def _do() -> None:
            box["row"] = self._read_file(cid)
        self.ladder.run(_do, surface="store_read",
                        what=f"fleet row {int(cid)} read")
        return box.get("row")

    def get(self, cid: int) -> Optional[Dict[str, np.ndarray]]:
        cid = int(cid)
        with self._ram_lock:
            row = self._rows.get(cid)
            if row is not None:
                self._rows.move_to_end(cid)
                return row
            row = self._spilling.get(cid)
            if row is not None:
                return row
        row = self._read_durable(cid)
        if row is not None:
            # the RAM copy inherits the on-disk generation's tag, so a
            # later clean re-spill is an idempotent same-file rewrite
            gen = self._newest_gen(cid)
            with self._ram_lock:
                self._tags[cid] = int(gen or 0)
            self._insert(cid, row, dirty=False)
        return row

    def put(self, cid: int, row: Dict[str, np.ndarray]) -> None:
        cid = int(cid)
        with self._ram_lock:
            self._tags[cid] = int(self.put_round)
        self._insert(cid, row, dirty=True)

    def _insert(self, cid: int, row: Dict[str, np.ndarray],
                dirty: bool) -> None:
        to_spill: List[tuple] = []
        with self._ram_lock:
            self._rows.pop(cid, None)
            self._rows[cid] = row
            if dirty:
                self._dirty.add(cid)
            while len(self._rows) > self.cache_rows:
                old_cid, old_row = self._rows.popitem(last=False)
                if old_cid in self._dirty:
                    # nowhere else holds the latest value: spill-through
                    # (file IO deferred past the lock; the row stays
                    # visible via _spilling until the write lands)
                    self._dirty.discard(old_cid)
                    self._spilling[old_cid] = old_row
                    to_spill.append((old_cid, old_row))
        for old_cid, old_row in to_spill:
            if self._write(old_cid, old_row):
                with self._ram_lock:
                    self._spilling.pop(old_cid, None)
                self.spilled_rows += 1
            # on exhausted retries the row STAYS in _spilling: still
            # served to peek/get, re-attempted at the next flush() —
            # a lost write degrades capacity, never correctness (the
            # ladder's escalator aborts a persistent outage)

    def _write(self, cid: int, row: Dict[str, np.ndarray]) -> bool:
        if self.store_dir is None:
            return True
        with self._ram_lock:
            gen = int(self._tags.get(cid, 0))
        path = self._path(cid, gen)
        tmp = path + ".tmp.npz"  # .npz suffix stops np.savez appending one

        def _do() -> None:
            np.savez(tmp, **row)
            os.replace(tmp, path)
        if self.ladder is None:
            _do()
            ok = True
        else:
            ok = self.ladder.run(_do, surface="store_write",
                                 what=f"fleet row {int(cid)} spill")
        if ok:
            self._register_gen(cid, gen)
        return ok

    def has_rows(self) -> bool:
        """Whether ANY client has a stored row (RAM or disk) — the
        cheap personalized-eval seen gate.  scandir short-circuits at
        the first row file: O(1), never an O(N)-filename listing."""
        if self._rows:
            return True
        if self.store_dir is None:
            return False
        with os.scandir(self.store_dir) as it:
            return any(entry.name.startswith("row_")
                       and ".tmp" not in entry.name for entry in it)

    # -- durability -----------------------------------------------------
    def flush(self) -> int:
        """Write every dirty RAM row through to disk; returns the row
        count (the spill transfer meter).  A row whose write exhausts
        its retries goes BACK on the dirty set (and stuck spill-through
        evictees re-attempt here too) — flush degrades to partial, never
        to silent loss."""
        if self.store_dir is None:
            self._dirty.clear()
            return 0
        n = 0
        with self._ram_lock:
            pending = [(cid, self._rows.get(cid))
                       for cid in sorted(self._dirty)]
            self._dirty.clear()
            stuck = sorted(self._spilling.items())
        for cid, row in pending:
            if row is None:
                continue
            if self._write(cid, row):
                n += 1
            else:
                with self._ram_lock:
                    if cid in self._rows:
                        self._dirty.add(cid)
        for cid, row in stuck:
            if self._write(cid, row):
                with self._ram_lock:
                    self._spilling.pop(cid, None)
                self.spilled_rows += 1
                n += 1
        return n

    def set_round(self, round_no: int) -> None:
        if self.store_dir is None:
            return
        path = self._marker_path()
        tmp = path + ".tmp.npy"

        def _do() -> None:
            np.save(tmp, np.asarray([int(round_no)], np.int64))
            os.replace(tmp, path)
        if self.ladder is None:
            _do()
        else:
            self.ladder.run(_do, surface="marker",
                            what=f"fleet round marker {int(round_no)}")

    def round(self) -> Optional[int]:
        return read_marker(self.store_dir)

    def reset(self) -> None:
        """Drop every row + marker (trajectory-mismatch semantics)."""
        with self._ram_lock:
            self._rows.clear()
            self._dirty.clear()
            self._spilling.clear()
            self._tags.clear()
            self._gens.clear()
        if self.store_dir is not None:
            self._wipe_files()


class CarryPager:
    """Shard-aware slot allocator + sharded page-in/writeback programs
    for ONE run's carry tables.  Allocator state is single-threaded by
    design: every mutating method is called from the server's round
    loop (prefetch -> prepare -> dispatch -> queue -> drain); the
    prefetch worker only stages row VALUES."""

    def __init__(self, strategy, state_tables: Dict[str, Any],
                 slots: int, mesh,
                 store_dir: Optional[str] = None,
                 host_cache_rows: int = 8192,
                 resume: bool = False,
                 partition_mode: str = "shard_map",
                 prefetch: bool = True,
                 ladder=None, faults=None):
        import jax
        from ..parallel.sharding import slot_pool_sharding

        self.strategy = strategy
        self.keys = tuple(strategy.carry_tables)
        if not self.keys:
            raise ValueError(
                f"{type(strategy).__name__} declares no carry_tables — "
                "fleet paging has nothing to page; drop the fleet block "
                "or use a device-carry strategy")
        self.n_slots = int(slots)
        self.mesh_shards = clients_axis_size(mesh)
        if self.n_slots % self.mesh_shards:
            raise ValueError(
                f"fleet.page_pool_slots={self.n_slots} does not split "
                f"over the {self.mesh_shards}-shard clients mesh axis — "
                "the server quantizes the pool to a mesh multiple; "
                "constructing CarryPager directly, do the same")
        #: per-shard block width: slot s lives on shard s // shard_slots
        self.shard_slots = self.n_slots // self.mesh_shards
        self.partition_mode = str(partition_mode)
        # per-key row geometry straight off the live tables (shape[0]
        # is the slot count; everything after is the row)
        self._row_shape = {}
        self._row_dtype = {}
        for k in self.keys:
            leaf = state_tables[k]
            if int(leaf.shape[0]) != self.n_slots:
                raise ValueError(
                    f"fleet paging: strategy_state[{k!r}] has "
                    f"{int(leaf.shape[0])} rows but the page pool is "
                    f"{self.n_slots} slots — carry_rows was not applied "
                    "before init_state")
            self._row_shape[k] = tuple(int(d) for d in leaf.shape[1:])
            self._row_dtype[k] = np.dtype(str(leaf.dtype))
        self._defaults = dict(strategy.carry_row_defaults())
        #: slot-axis tables and page-in/writeback buffers are SHARDED
        #: over the clients axis — per-device bytes = total/mesh_size
        self._pool_spec = slot_pool_sharding(mesh)
        #: one DurableIOLadder governs the store's spill/read/marker IO
        #: AND this pager's writeback fetch; the chaos InfraFaults (if
        #: any) supplies the prefetch-surface hooks below
        self.ladder = ladder
        self._infra = faults
        self._prefetch_fault = (faults.hook("prefetch")
                                if faults is not None else None)
        self.store = FleetRowStore(store_dir, cache_rows=host_cache_rows,
                                   resume=resume, ladder=ladder)

        # ---- slot state (per shard) ----------------------------------
        self._free: List[List[int]] = [
            list(range((s + 1) * self.shard_slots - 1,
                       s * self.shard_slots - 1, -1))
            for s in range(self.mesh_shards)]
        self._slot_client = np.full((self.n_slots,), -1, np.int64)
        self._client_slot: Dict[int, int] = {}
        self._pins = np.zeros((self.n_slots,), np.int64)
        #: per-shard unpinned slots in LRU order (front = evict first)
        self._lru: List["OrderedDict[int, None]"] = [
            OrderedDict() for _ in range(self.mesh_shards)]
        self._ticket: Optional[Dict[str, Any]] = None
        #: queued-but-uncompleted writeback handles, dispatch order —
        #: what a shard-migration force-completes to unpin old slots
        self._outstanding: deque = deque()

        # ---- prefetch staging ----------------------------------------
        self.prefetch_enabled = bool(prefetch)
        #: set on the first prefetch_chunk call — hit/miss accounting
        #: starts only once the server actually ENGAGES prefetch (a
        #: serial or sample-hooked run never does; its cold page-ins
        #: must not read as a 0.0 hit rate to the scope diff gate)
        self._prefetch_engaged = False
        self._staging: Dict[int, Optional[Dict[str, np.ndarray]]] = {}
        self._staging_lock = threading.Lock()
        self._prefetch_thread: Optional[threading.Thread] = None
        #: optional flutescope Telemetry (the server wires it): the
        #: worker opens a `fleet_prefetch` span on its OWN thread
        #: track, so the trace shows the paging host IO overlapping
        #: the device window instead of sitting on the critical path
        self.scope = None

        # ---- compiled program caches (one per pow2 width) ------------
        self._scatter_cache: Dict[int, Any] = {}
        self._gather_cache: Dict[int, Any] = {}
        self._jax = jax

        # ---- counters (bench marker + devbus gauges) -----------------
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.migrations = 0
        self.forced_drains = 0
        self.page_in_rows = 0
        self.writeback_rows = 0
        self.page_in_bytes = 0
        self.writeback_bytes = 0
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.prefetch_degradations = 0

    # ------------------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        total_pf = self.prefetch_hits + self.prefetch_misses
        return {
            "pool_slots": self.n_slots,
            "mesh_shards": int(self.mesh_shards),
            "shard_slots": int(self.shard_slots),
            "resident": int(len(self._client_slot)),
            "hits": int(self.hits),
            "misses": int(self.misses),
            "evictions": int(self.evictions),
            "migrations": int(self.migrations),
            "forced_drains": int(self.forced_drains),
            "page_in_rows": int(self.page_in_rows),
            "writeback_rows": int(self.writeback_rows),
            "page_in_bytes": int(self.page_in_bytes),
            "page_in_bytes_per_device":
                int(self.page_in_bytes // self.mesh_shards),
            "writeback_bytes": int(self.writeback_bytes),
            "writeback_bytes_per_device":
                int(self.writeback_bytes // self.mesh_shards),
            "prefetch_hits": int(self.prefetch_hits),
            "prefetch_misses": int(self.prefetch_misses),
            "prefetch_degradations": int(self.prefetch_degradations),
            # None (not 0.0) when prefetch never engaged: a serial /
            # sample-hooked / prefetch-off run has no coverage to
            # report, and a 0.0 would trip the scope-diff hit-rate gate
            # against any prefetching baseline
            "prefetch_hit_rate": (float(self.prefetch_hits) / total_pf
                                  if total_pf else
                                  (0.0 if self._prefetch_engaged
                                   else None)),
            "spilled_rows": int(self.store.spilled_rows),
            "hbm_bytes_per_device":
                int(self.shard_slots * self.hbm_row_bytes()),
            "tables": list(self.keys),
        }

    def hbm_row_bytes(self) -> int:
        """Bytes one pool row costs across all table keys — PER-DEVICE
        pool HBM is ``shard_slots * hbm_row_bytes()`` (the slot axis is
        sharded), independent of N."""
        return int(sum(
            int(np.prod(self._row_shape[k], dtype=np.int64) or 1)
            * self._row_dtype[k].itemsize for k in self.keys))

    def pool_sharding(self):
        """The slot-axis NamedSharding the engine puts the carry tables
        with (``P(CLIENTS_AXIS)`` on axis 0)."""
        return self._pool_spec

    # ------------------------------------------------------------------
    # slot allocation (shard-aware)
    # ------------------------------------------------------------------
    def _shard_of(self, slot: int) -> int:
        return slot // self.shard_slots

    def _pin(self, slot: int) -> None:
        if self._pins[slot] == 0:
            self._lru[self._shard_of(slot)].pop(slot, None)
        self._pins[slot] += 1

    def _unpin(self, slot: int) -> None:
        self._pins[slot] -= 1
        if self._pins[slot] <= 0:
            self._pins[slot] = 0
            if self._slot_client[slot] >= 0:
                # tail = most recently used
                self._lru[self._shard_of(slot)][slot] = None

    def _force_drain_oldest(self) -> bool:
        """Complete the oldest outstanding writeback early (an explicit
        fetch of an already-dispatched gather — the value is the
        post-chunk rows, so the host store is current afterwards).
        Unblocks shard migrations and per-shard slot contention."""
        if not self._outstanding:
            return False
        self.forced_drains += 1
        self.complete_writeback(self._outstanding[0])
        return True

    def _alloc(self, cid: int, shard: int) -> int:
        while True:
            if self._free[shard]:
                slot = self._free[shard].pop()
                break
            if self._lru[shard]:
                slot, _ = self._lru[shard].popitem(last=False)  # LRU head
                old = int(self._slot_client[slot])
                # the host store already holds the evictee's current
                # row: unpinned means every chunk that touched it
                # drained, and the drain wrote the row back — eviction
                # costs zero device traffic
                self._client_slot.pop(old, None)
                self.evictions += 1
                break
            # every slot of this shard is pinned by an in-flight chunk:
            # drain the oldest outstanding writeback (early explicit
            # fetch) and retry — only a pool too small overall gives up
            if not self._force_drain_oldest():
                raise ValueError(
                    f"fleet.page_pool_slots={self.n_slots} cannot hold "
                    f"the in-flight cohorts: every slot of shard {shard} "
                    f"({self.shard_slots} of {self.n_slots}) is pinned "
                    "by a dispatched chunk — raise page_pool_slots (it "
                    "must cover (pipeline_depth + 1) x cohort x "
                    "rounds_per_step rows per shard)")
        self._slot_client[slot] = cid
        self._client_slot[cid] = slot
        return slot

    def _migrate_out(self, cid: int, slot: int) -> None:
        """Free a client's slot on the wrong shard so it can re-alloc
        on the shard that computes its lane.  An in-flight pin means an
        undrained chunk still owns the row — force-complete writebacks
        (oldest first) until the pin drops, so the host store holds the
        post-chunk value before the migration pages it back in."""
        while self._pins[slot] > 0:
            if not self._force_drain_oldest():
                raise RuntimeError(
                    "fleet pager: slot pinned with no outstanding "
                    "writeback — prepare/queue discipline broken")
        shard = self._shard_of(slot)
        self._lru[shard].pop(slot, None)
        self._client_slot.pop(cid, None)
        self._slot_client[slot] = -1
        self._free[shard].append(slot)
        self.migrations += 1

    # ------------------------------------------------------------------
    # prefetch (host-side async stage of next chunk's missing rows)
    # ------------------------------------------------------------------
    def prefetch_chunk(self, batches: list) -> int:
        """Stage the NEXT chunk's missing rows on a background thread
        while the device executes the current one.  Read-only against
        the store (peek + direct file read) — the allocator and LRU
        stay single-threaded, and a staged value cannot go stale: a
        client missing from the pool is in no in-flight chunk, so no
        writeback can update its row before the next prepare_chunk
        consumes the staging.  Returns the number of rows queued."""
        if not self.prefetch_enabled:
            return 0
        self._prefetch_engaged = True
        self._join_prefetch()
        flat = [b for entry in batches
                for b in (entry if isinstance(entry, list) else [entry])]
        want: List[int] = []
        seen: set = set()
        for b in flat:
            for cid in np.asarray(b.client_ids).ravel():
                cid = int(cid)
                if cid < 0 or cid in seen or cid in self._client_slot:
                    continue
                seen.add(cid)
                want.append(cid)
        with self._staging_lock:
            self._staging = {}
            staging = self._staging
        if not want:
            return 0
        t = threading.Thread(
            target=self._prefetch_worker, args=(want, staging),
            name="fleet-prefetch", daemon=True)
        self._prefetch_thread = t
        t.start()
        return len(want)

    def _prefetch_worker(self, cids: List[int], staging: dict) -> None:
        try:
            scope = self.scope
            if scope is not None:
                with scope.span("fleet_prefetch", rows=len(cids)):
                    self._prefetch_rows(cids, staging)
            else:
                self._prefetch_rows(cids, staging)
        except Exception as exc:  # noqa: BLE001 - any death must degrade
            self._degrade_prefetch(exc)

    def _degrade_prefetch(self, exc: BaseException) -> None:
        """The fleet-prefetch daemon died (injected chaos fault or a
        real one): permanently fall back to COLD paging — every later
        miss takes the synchronous ``store.get`` path, which loads the
        exact same values (bit-identical by the staging contract), just
        on the critical path.  One structured ``prefetch_degraded``
        instant event surfaces it; the thread never dies silently into
        a dead staging generation."""
        self.prefetch_enabled = False
        self.prefetch_degradations += 1
        with self._staging_lock:
            self._staging = {}
        emit_event(self.scope, "prefetch_degraded",
                   error=repr(exc),
                   degradations=int(self.prefetch_degradations))

    def _prefetch_rows(self, cids: List[int], staging: dict) -> None:
        store = self.store
        infra = self._infra
        if infra is not None:
            # seeded staging stall: exercises the superseded-generation
            # path (prepare_chunk clears a half-filled staging dict and
            # the loop below notices and stops) without killing the
            # worker
            delay = infra.prefetch_delay()
            if delay > 0.0:
                time.sleep(delay)
        for cid in cids:
            if self._prefetch_fault is not None:
                self._prefetch_fault()
            row = store.peek(cid)
            if row is None:
                row = store._read_file(cid)
            with self._staging_lock:
                if staging is not self._staging:
                    return  # superseded generation: stop loading
                staging[cid] = row

    def _join_prefetch(self) -> None:
        t = self._prefetch_thread
        if t is not None and t.is_alive():
            t.join()
        self._prefetch_thread = None

    def _load_row(self, cid: int) -> Optional[Dict[str, np.ndarray]]:
        """A miss's row: the prefetch staging if the worker got there
        (hit — host IO already off the critical path), else the
        synchronous store read (cold path; bit-identical values)."""
        if self._prefetch_engaged:
            with self._staging_lock:
                if cid in self._staging:
                    self.prefetch_hits += 1
                    return self._staging.pop(cid)
            self.prefetch_misses += 1
        return self.store.get(cid)

    # ------------------------------------------------------------------
    # per-chunk flow
    # ------------------------------------------------------------------
    def prepare_chunk(self, batches: list, strategy_state: Any) -> Any:
        """Map the chunk's cohorts onto pool slots (writes
        ``batch.carry_slots`` on every grid — GLOBAL slot ids; the
        engine converts to shard-local indices inside ``shard_map`` —
        -1 for padding lanes), page missing rows in as one fixed-shape
        donated SHARDED scatter, and pin the touched slots until this
        chunk drains.  Slot placement follows ``lane_shard_map``: each
        lane's row lands on the shard that computes it.  Returns the
        (possibly updated) ``strategy_state``."""
        if self._ticket is not None:
            raise RuntimeError(
                "fleet pager: prepare_chunk called with an unconsumed "
                "ticket — queue_writeback must run after each dispatch")
        flat = [b for entry in batches
                for b in (entry if isinstance(entry, list) else [entry])]
        chunk_slots: "OrderedDict[int, int]" = OrderedDict()  # slot->cid
        chunk_shard: Dict[int, int] = {}  # cid -> required shard
        miss: List[tuple] = []
        for b in flat:
            ids = np.asarray(b.client_ids)
            shards = lane_shard_map(ids.shape[0], self.mesh_shards)
            slots = np.full(ids.shape, -1, np.int32)
            for j, cid in enumerate(ids):
                cid = int(cid)
                if cid < 0:
                    continue
                shard = int(shards[j])
                prev = chunk_shard.get(cid)
                if prev is not None and prev != shard:
                    # the server refuses rounds_per_step > 1 on a >1-
                    # shard mesh exactly because this row dependency
                    # cannot be satisfied without a cross-shard
                    # collective; reaching here is a logic error
                    raise RuntimeError(
                        f"fleet pager: client {cid} appears on shards "
                        f"{prev} and {shard} within one chunk — "
                        "mid-chunk cross-shard carry reuse is "
                        "unsupported (rounds_per_step must be 1 on a "
                        "multi-device mesh)")
                chunk_shard[cid] = shard
                slot = self._client_slot.get(cid)
                if slot is not None and self._shard_of(slot) != shard:
                    # resampled onto a different shard: free the old
                    # slot (force-draining its in-flight writeback if
                    # needed) and treat as a miss on the new shard —
                    # the host store holds the current row
                    self._migrate_out(cid, slot)
                    slot = None
                if slot is None:
                    slot = self._alloc(cid, shard)
                    miss.append((cid, slot))
                    self.misses += 1
                else:
                    self.hits += 1
                    shard_lru = self._lru[shard]
                    if self._pins[slot] == 0 and slot in shard_lru:
                        shard_lru.move_to_end(slot)
                slots[j] = slot
                if slot not in chunk_slots:
                    chunk_slots[slot] = cid
                    self._pin(slot)
            b.carry_slots = slots
        page_in_bytes = 0
        if miss:
            strategy_state, page_in_bytes = \
                self._page_in(strategy_state, miss)
        self._ticket = {
            "slots": np.asarray(list(chunk_slots), np.int32),
            "ids": np.asarray(list(chunk_slots.values()), np.int64),
            "page_in_bytes": int(page_in_bytes),
        }
        if self.prefetch_enabled:
            # generation boundary: anything the worker staged for this
            # chunk and nobody consumed is dead weight now
            with self._staging_lock:
                self._staging = {}
        return strategy_state

    def _page_in(self, strategy_state: Any, miss: List[tuple]) -> tuple:
        jax = self._jax
        M, SS = self.mesh_shards, self.shard_slots
        per_shard: List[List[tuple]] = [[] for _ in range(M)]
        for cid, slot in miss:
            per_shard[self._shard_of(slot)].append((cid, slot))
        W = _pow2_width(max(len(g) for g in per_shard))
        local_ids = self.partition_mode == "shard_map"
        # sentinel index: one past the (local or global) slot range —
        # out of bounds, mode="drop", so padded lanes scatter nothing
        sentinel = SS if local_ids else self.n_slots
        slot_arr = np.full((M * W,), sentinel, np.int32)
        rows = {k: np.full((M * W,) + self._row_shape[k],
                           self._defaults.get(k, 0.0),
                           self._row_dtype[k]) for k in self.keys}
        for s, group in enumerate(per_shard):
            for i, (cid, slot) in enumerate(group):
                slot_arr[s * W + i] = (slot - s * SS) if local_ids \
                    else slot
                stored = self._load_row(cid)
                if stored is not None:
                    for k in self.keys:
                        rows[k][s * W + i] = stored[k]
        self.page_in_rows += len(miss)
        nbytes = int(sum(r.nbytes for r in rows.values())
                     + slot_arr.nbytes)
        self.page_in_bytes += nbytes
        fn = self._scatter_cache.get(W)
        if fn is None:
            fn = self._build_scatter(W)
            self._scatter_cache[W] = fn
        tables = {k: strategy_state[k] for k in self.keys}
        # ONE sharded put for the whole padded row dict: the leading
        # axis is P(CLIENTS_AXIS), so each device receives only its own
        # [W] segment — per-device page-in bytes = total / mesh_size
        rows_dev = jax.device_put(rows, self._pool_spec)
        slots_dev = jax.device_put(slot_arr, self._pool_spec)
        new_tables = fn(tables, slots_dev, rows_dev)
        new_state = dict(strategy_state)
        new_state.update(new_tables)
        return new_state, nbytes

    def _build_scatter(self, W: int):
        jax = self._jax
        keys = self.keys

        def scatter(tables, slots, new_rows):
            # sentinel-padded lanes target one past the slot range:
            # out of bounds, mode="drop" — the fixed [M*W] shape never
            # retraces on the miss count
            return {k: tables[k].at[slots].set(new_rows[k], mode="drop")
                    for k in keys}

        if self.partition_mode == "shard_map":
            from jax.sharding import PartitionSpec as P
            from ..utils.compat import shard_map
            cspec = P(CLIENTS_AXIS)
            scatter = shard_map(
                scatter, mesh=self._pool_spec.mesh,
                in_specs=(cspec, cspec, cspec), out_specs=cspec,
                check_vma=False)
        return jax.jit(scatter, donate_argnums=(0,))

    def _build_gather(self, W: int):
        jax = self._jax
        import jax.numpy as jnp
        keys = self.keys
        hi = (self.shard_slots if self.partition_mode == "shard_map"
              else self.n_slots) - 1

        def gather(tables, slots):
            idx = jnp.clip(slots, 0, hi)
            return {k: tables[k][idx] for k in keys}

        if self.partition_mode == "shard_map":
            from jax.sharding import PartitionSpec as P
            from ..utils.compat import shard_map
            cspec = P(CLIENTS_AXIS)
            gather = shard_map(
                gather, mesh=self._pool_spec.mesh,
                in_specs=(cspec, cspec), out_specs=cspec,
                check_vma=False)
        return jax.jit(gather)

    def queue_writeback(self, strategy_state: Any,
                        round_no: int = 0) -> Dict[str, Any]:
        """Dispatch the async per-shard gather of this chunk's slot
        rows from the POST-chunk tables.  Must run before the next
        dispatch donates ``strategy_state`` (program order then
        guarantees the gather reads the chunk's output).  ``round_no``
        is the chunk's LAST round — the generation tag the drained rows
        spill under (the crash-point rollback anchor).  Returns the
        handle the drain completes (idempotently — a shard migration
        may have force-completed it early)."""
        ticket = self._ticket
        self._ticket = None
        if ticket is None or ticket["slots"].size == 0:
            return {"ids": np.empty((0,), np.int64), "rows": None,
                    "slots": np.empty((0,), np.int32),
                    "pos": np.empty((0,), np.int64), "done": True,
                    "round": int(round_no),
                    "page_in_bytes": int((ticket or {}).get(
                        "page_in_bytes", 0)),
                    "writeback_bytes": 0}
        jax = self._jax
        M, SS = self.mesh_shards, self.shard_slots
        per_shard: List[List[int]] = [[] for _ in range(M)]
        order: List[int] = []  # ticket index in segment-layout order
        for i, slot in enumerate(ticket["slots"]):
            per_shard[self._shard_of(int(slot))].append(i)
        W = _pow2_width(max(len(g) for g in per_shard))
        local_ids = self.partition_mode == "shard_map"
        slot_arr = np.zeros((M * W,), np.int32)
        pos = np.empty((ticket["slots"].size,), np.int64)
        n = 0
        for s, group in enumerate(per_shard):
            for i, tick_i in enumerate(group):
                slot = int(ticket["slots"][tick_i])
                slot_arr[s * W + i] = (slot - s * SS) if local_ids \
                    else slot
                pos[n] = s * W + i
                order.append(tick_i)
                n += 1
        fn = self._gather_cache.get(W)
        if fn is None:
            fn = self._build_gather(W)
            self._gather_cache[W] = fn
        tables = {k: strategy_state[k] for k in self.keys}
        slots_dev = jax.device_put(slot_arr, self._pool_spec)
        rows = fn(tables, slots_dev)
        wb_bytes = int(sum(
            int(np.prod((M * W,) + self._row_shape[k], dtype=np.int64))
            * self._row_dtype[k].itemsize for k in self.keys))
        self.writeback_bytes += wb_bytes
        handle = {"ids": ticket["ids"][order],
                  "slots": ticket["slots"][order],
                  "pos": pos, "rows": rows, "done": False,
                  "round": int(round_no),
                  "page_in_bytes": int(ticket["page_in_bytes"]),
                  "writeback_bytes": wb_bytes}
        self._outstanding.append(handle)
        return handle

    def complete_writeback(self, handle: Dict[str, Any]) -> None:
        """Drain half: ONE explicit fetch of the gathered rows — the
        per-shard slices of the sharded gather output come back in the
        one ``device_get`` — written through to the host store; the
        chunk's slots unpin.  Idempotent: a shard migration may have
        force-completed this handle before the pipeline drain reaches
        it."""
        if handle.get("done"):
            return
        handle["done"] = True
        # identity scan, not deque.remove: == on handle dicts would
        # element-wise compare their numpy members
        for i, h in enumerate(self._outstanding):
            if h is handle:
                del self._outstanding[i]
                break
        ids = handle["ids"]
        if handle["rows"] is None or ids.size == 0:
            return
        jax = self._jax
        if self.ladder is None:
            fetched = jax.device_get(handle["rows"])
        else:
            # transient fetch failures retry under the ladder; an
            # exhausted fetch raises DurableIOError (these are the
            # post-chunk carry rows — losing them corrupts training)
            box: Dict[str, Any] = {}

            def _fetch() -> None:
                box["v"] = jax.device_get(handle["rows"])
            self.ladder.run(_fetch, surface="writeback",
                            what=f"fleet writeback of {int(ids.size)} rows")
            fetched = box["v"]
        pos = handle["pos"]
        # the rows about to land carry this chunk's final round as
        # their generation tag (crash-point rollback selects on it)
        self.store.put_round = int(handle.get("round", 0))
        for i, cid in enumerate(ids):
            # np.array (copy), not np.asarray (view): a view would pin
            # the whole padded [M*W] fetch buffer in the host row cache
            self.store.put(int(cid),
                           {k: np.array(fetched[k][pos[i]])
                            for k in self.keys})
        self.writeback_rows += int(ids.size)
        for slot in handle["slots"]:
            self._unpin(int(slot))

    # ------------------------------------------------------------------
    # host-side reads (personalized eval) + durability
    # ------------------------------------------------------------------
    def user_row(self, uid: int) -> Optional[Dict[str, np.ndarray]]:
        """The client's CURRENT carry row from the host store (valid at
        any drained boundary — eval boundaries fully drain the ring),
        or None for a never-participated client."""
        return self.store.get(int(uid))

    def has_rows(self) -> bool:
        return self.store.has_rows()

    def flush(self) -> int:
        return self.store.flush()

    def set_round(self, round_no: int) -> None:
        self.store.set_round(round_no)

    def round(self) -> Optional[int]:
        return self.store.round()

    def adopt_round(self, round_no: int) -> None:
        self.store.adopt_round(round_no)

    def mark_durable(self, round_no: int) -> None:
        self.store.mark_durable(round_no)

    def reset(self) -> None:
        """Trajectory mismatch on resume: drop the host rows AND the
        slot map — every next touch cold-starts from the defaults,
        exactly like a fresh table."""
        self._join_prefetch()
        self.store.reset()
        self._free = [
            list(range((s + 1) * self.shard_slots - 1,
                       s * self.shard_slots - 1, -1))
            for s in range(self.mesh_shards)]
        self._slot_client[:] = -1
        self._client_slot.clear()
        self._pins[:] = 0
        for lru in self._lru:
            lru.clear()
        self._ticket = None
        self._outstanding.clear()
        with self._staging_lock:
            self._staging = {}
