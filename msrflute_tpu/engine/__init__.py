from .client_update import build_client_update, ClientHParams  # noqa: F401
from .round import RoundEngine, ServerState  # noqa: F401
from .evaluation import build_eval_fn, evaluate  # noqa: F401
from .server import OptimizationServer, select_server  # noqa: F401
