"""Distributed evaluation.

Parity target: reference ``core/evaluation.py`` + ``run_validation_generic``
(``core/trainer.py:690-723``) + ``Metrics.call_inference``
(``core/metrics.py:29-73``): eval users are chunked across workers
(``core/evaluation.py:185-216``), each runs the model over its shard, and
metrics are sample-weighted averaged server-side
(``core/evaluation.py:160-183``).

TPU-native: all eval samples are packed into a ``[T, B, ...]`` grid
(:func:`msrflute_tpu.data.batching.pack_eval_batches`), the batch axis T is
sharded over the mesh's ``clients`` axis, a ``lax.scan`` accumulates each
task's *sum*-form eval stats, and one ``psum`` merges shards — numerically
identical to the reference's weighted average, in one compiled program.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..utils.compat import shard_map

from ..models.base import BaseTask
from ..parallel.mesh import CLIENTS_AXIS
from ..utils.metrics import MetricsDict


def build_eval_fn(task: BaseTask, mesh: Mesh,
                  partition_mode: str = "shard_map") -> Callable:
    """Returns jitted ``eval_fn(params, batches) -> stat sums`` where
    ``batches`` is the dict from ``pack_eval_batches`` (leading axis T padded
    to a multiple of the clients-axis size).  ``partition_mode='gspmd'``
    skips the explicit shard_map/psum so model-sharded params work (XLA
    partitions the scan body itself)."""
    cspec = P(CLIENTS_AXIS)
    rspec = P()

    def shard_body(params, batches):
        batches = {k: v for k, v in batches.items() if k != "user_idx"}

        def body(carry, batch):
            sums, skipped = carry
            step = task.eval_stats(params, batch)
            # eval-side non-finite guard (fluteshield): a single client
            # batch producing a NaN/Inf stat would otherwise poison the
            # whole split's sums — and through best_val/plateau, the LR
            # schedule's history, permanently.  A poisoned step's ENTIRE
            # contribution (including its sample_count) is excluded, so
            # the surviving weighted average stays consistent; the
            # skipped-step count rides out with the sums for the
            # structured `eval_nonfinite_skipped` event.  All-finite
            # evals are numerically identical (where(True) is identity).
            finite = jnp.asarray(True)
            for leaf in jax.tree.leaves(step):
                if jnp.issubdtype(leaf.dtype, jnp.floating):
                    finite = finite & jnp.all(jnp.isfinite(leaf))
            step = jax.tree.map(
                lambda s: jnp.where(finite, s, jnp.zeros_like(s)), step)
            return (jax.tree.map(jnp.add, sums, step),
                    skipped + (1.0 - finite.astype(jnp.float32))), None

        # zero-initialize the carry; zeros_like only needs shapes, so the
        # extra eval_stats trace is dead-code-eliminated by XLA
        first = {k: v[0] for k, v in batches.items()}
        zero = jax.tree.map(jnp.zeros_like, task.eval_stats(params, first))
        (sums, skipped), _ = jax.lax.scan(
            body, (zero, jnp.zeros((), jnp.float32)), batches)
        if partition_mode == "shard_map":
            sums = jax.lax.psum(sums, CLIENTS_AXIS)
            skipped = jax.lax.psum(skipped, CLIENTS_AXIS)
        sums = dict(sums)
        sums["__eval_nonfinite_steps__"] = skipped
        return sums

    if partition_mode == "shard_map":
        fn = shard_map(shard_body, mesh=mesh,
                       in_specs=(rspec, cspec), out_specs=rspec,
                       check_vma=False)
    else:
        fn = shard_body
    return jax.jit(fn)


def build_per_user_eval_fn(task: BaseTask, mesh: Mesh, n_users: int,
                           partition_mode: str = "shard_map") -> Callable:
    """Jitted ``(params, batches) -> (correct [n_users], count [n_users])``
    classification accuracy segmented by the eval grid's ``user_idx``.

    Fairness observability (the q-FFL / AFL complement — aggregate
    accuracy hides the client dispersion those strategies optimize): one
    scan over the same packed eval grid the metric eval uses, with
    per-sample correctness scattered into per-user sums
    (``.at[].add(mode="drop")``; padding rows map out of bounds).
    Requires a classification-style task (``task.apply`` + ``y`` labels).
    """
    cspec = P(CLIENTS_AXIS)
    rspec = P()

    def shard_body(params, batches):
        def body(carry, batch):
            c, t = carry
            pred = jnp.argmax(task.apply(params, batch["x"]), axis=-1)
            correct = (pred == batch["y"].astype(jnp.int32)).astype(
                jnp.float32) * batch["sample_mask"]
            uid = batch["user_idx"]
            # -1 padding must NOT wrap to the last user: send it out of
            # bounds so mode="drop" discards it
            uid = jnp.where(uid >= 0, uid, n_users)
            c = c.at[uid].add(correct, mode="drop")
            t = t.at[uid].add(batch["sample_mask"], mode="drop")
            return (c, t), None

        zero = (jnp.zeros((n_users,), jnp.float32),
                jnp.zeros((n_users,), jnp.float32))
        (c, t), _ = jax.lax.scan(body, zero, batches)
        if partition_mode == "shard_map":
            c = jax.lax.psum(c, CLIENTS_AXIS)
            t = jax.lax.psum(t, CLIENTS_AXIS)
        return c, t

    if partition_mode == "shard_map":
        fn = shard_map(shard_body, mesh=mesh,
                       in_specs=(rspec, cspec), out_specs=rspec,
                       check_vma=False)
    else:
        fn = shard_body
    return jax.jit(fn)


def per_user_accuracy(per_user_fn: Callable, params: Any,
                      batches: Dict[str, np.ndarray], mesh: Mesh,
                      partition_mode: str = "shard_map") -> np.ndarray:
    """Per-user accuracy vector (NaN where a user had no eval samples)."""
    spec = P(CLIENTS_AXIS) if partition_mode == "shard_map" else P()
    sharding = NamedSharding(mesh, spec)
    # flint: disable=put-loop eval-boundary staging, not the per-round dispatch path
    staged = {k: jax.device_put(v, sharding) for k, v in batches.items()}
    c, t = jax.device_get(per_user_fn(params, staged))
    c, t = np.asarray(c, np.float64), np.asarray(t, np.float64)
    with np.errstate(invalid="ignore", divide="ignore"):
        return np.where(t > 0, c / np.maximum(t, 1.0), np.nan)


def evaluate(task: BaseTask, eval_fn: Callable, params: Any,
             batches: Dict[str, np.ndarray], mesh: Mesh,
             partition_mode: str = "shard_map",
             telemetry=None) -> MetricsDict:
    """Run the jitted eval program and finalize metrics host-side.

    In shard_map mode the batch-step axis T is sharded over ``clients``
    (data-parallel eval); in gspmd mode batches stay replicated and the
    model axis shards the compute instead (a scan cannot iterate a sharded
    leading axis without resharding every step).

    ``telemetry``: optional flutescope scope — the device program +
    stat-sums fetch becomes its own ``eval_device`` span so a trace
    separates eval device time from the host metric finalize.
    """
    spec = P(CLIENTS_AXIS) if partition_mode == "shard_map" else P()
    sharding = NamedSharding(mesh, spec)
    # flint: disable=put-loop eval-boundary staging, not the per-round dispatch path
    staged = {k: jax.device_put(v, sharding) for k, v in batches.items()}
    if telemetry is not None:
        with telemetry.span("eval_device"):
            sums = jax.device_get(eval_fn(params, staged))
    else:
        sums = jax.device_get(eval_fn(params, staged))
    sums = dict(sums)
    skipped = float(sums.pop("__eval_nonfinite_steps__", 0.0))
    metrics = task.finalize_metrics(sums)
    if skipped:
        from ..telemetry import emit_event
        # structured record in the metrics stream (and trace when on):
        # the split's aggregate EXCLUDED this many poisoned batch steps
        emit_event(telemetry, "eval_nonfinite_skipped",
                   steps=int(skipped))
        if float(sums.get("sample_count", 0.0)) <= 0.0:
            # EVERY step was poisoned: the zero-sum "metrics" would read
            # as a perfect loss of 0.0 and hijack best_val — surface NaN
            # so the server's finite gate skips best/plateau updates
            from ..utils.metrics import Metric
            metrics = {name: Metric(float("nan"), m.higher_is_better)
                       for name, m in metrics.items()}
    return metrics
