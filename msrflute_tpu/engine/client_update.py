"""Per-client local training as a pure jittable function.

Parity target: reference ``Client.process_round`` + ``Trainer``
(``core/client.py:226-511``, ``core/trainer.py:200-687``).  Semantics
preserved exactly (SURVEY.md §7):

- model reset per client: local params start from the server's globals
  (``core/client.py:294-302``) — here simply the function argument;
- fresh optimizer per client with the server-dictated LR
  (``core/client.py:309-312``) — optax init inside the function;
- per-batch loss -> grad -> clip -> stats -> step
  (``core/trainer.py:341-414``) — ONE ``lax.scan`` over the flattened
  ``[num_epochs * steps]`` grid (megakernel epoch fusion, PR 12: the body
  is traced once whatever the epoch count; ``megakernel.fused_epochs:
  false`` restores the legacy one-scan-per-epoch unrolled trace, which is
  bit-identical in f32 but whose program text grows linearly in epochs);
- ``desired_max_samples`` early stop (``core/trainer.py:363-364``) — encoded
  in the batch packing (zero-mask beyond the cap), with all-padding steps
  gated so they change nothing;
- FedProx proximal term ``mu * (w - w_global)`` added to gradients
  (``core/trainer.py:416-501``);
- pseudo-gradient = w_server - w_trained (``core/client.py:380-383``);
- gradient sufficient stats accumulated per batch
  (``core/trainer.py:263-312``): ``sum``, ``sq_sum``, ``n``, and derived
  ``mean = sum/n``, ``mag = sqrt(sq_sum/n)``, ``norm = sqrt(sq_sum)``.
  NOTE the reference computes ``var = sq_sum/n - mag**2`` which is
  identically zero (``core/trainer.py:301``); we keep that key for parity
  but also expose the statistically meaningful ``var_corrected =
  sq_sum/n - mean**2``.
- per-layer freezing (``core/client.py:306-307``): frozen layers get zero
  pseudo-gradient, equivalent to the reference's zeroed ``p.grad``.

This function is ``vmap``-ed over the round's clients and ``shard_map``-ed
over the mesh by :mod:`msrflute_tpu.engine.round` — the role FLUTE's Worker
processes play (``core/federated.py:482-632``), with no RPC.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..models.base import BaseTask
from ..optim import make_optimizer
from ..optim.fused import (combine_grad_terms, fused_apply, segment_select,
                           sgd_pallas_fusable)


@dataclass(frozen=True)
class ClientHParams:
    """Static client-update hyperparameters (compiled into the program)."""

    max_grad_norm: Optional[float] = None       # core/trainer clip
    fedprox_mu: float = 0.0                     # FedProx proximal weight
    num_epochs: int = 1                         # local epochs per round
    stats_on_smooth_grad: bool = True           # dga.py:104-108
    freeze_layers: Tuple[str, ...] = ()         # core/client.py:306-307
    #: regex allowlist — when set, ONLY matching layers move; the rest are
    #: frozen at every inner step, like the reference's per-param lr=0
    #: (set_component_wise_lr, core/trainer.py:725-751)
    updatable_layers: Optional[Tuple[str, ...]] = None
    #: megakernel epoch fusion (default ON): run all ``num_epochs *
    #: steps`` local steps as ONE ``lax.scan`` instead of cloning the
    #: step-scan body once per epoch — program size and compile time
    #: stay flat in num_epochs (the PR-12 bloat fix;
    #: ``server_config.megakernel.fused_epochs: false`` restores the
    #: legacy unrolled trace for A/Bs).  num_epochs == 1 traces the
    #: exact historical program either way.
    fused_epochs: bool = True
    #: opt-in pallas fused SGD apply (``server_config.megakernel.
    #: pallas_apply``): the inner step's optimizer tail runs as ONE
    #: kernel pass over the flattened param vector
    #: (``ops.pallas_kernels.fused_sgd_apply``) instead of per-leaf XLA
    #: ops — for small-model protocols whose leaves are too tiny to
    #: tile.  Plain-SGD optimizers only (momentum ok); TPU-targeted
    #: (interpret mode elsewhere).
    pallas_apply: bool = False
    #: precision policy (``server_config.precision``), each a dtype name
    #: or None.  ``compute`` casts params + float batch features for the
    #: forward/backward only (grads come back in the params dtype — the
    #: f32 master-params discipline); ``params`` holds the client's
    #: LOCAL working copy (and optimizer state) in that dtype;
    #: ``stats`` sets the loss/grad-stat accumulator dtype.  None (or
    #: "float32") compiles the exact f32 legacy trace — the bit-identity
    #: default.
    param_dtype: Optional[str] = None
    compute_dtype: Optional[str] = None
    stats_dtype: Optional[str] = None


def _global_norm(tree: Any) -> jnp.ndarray:
    return optax.global_norm(tree)


def _clip_by_global_norm(tree: Any, max_norm: float) -> Any:
    norm = _global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: g * scale, tree)


def _suff_stats_of(tree: Any) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    leaves = jax.tree.leaves(tree)
    s = sum(jnp.sum(g) for g in leaves)
    s2 = sum(jnp.sum(g * g) for g in leaves)
    n = float(sum(g.size for g in leaves))
    return s, s2, jnp.asarray(n)


def _derive_stats(s, s2, n) -> Dict[str, jnp.ndarray]:
    n = jnp.maximum(n, 1.0)
    mean = s / n
    mag = jnp.sqrt(s2 / n)
    return {
        "sum": s,
        "sq_sum": s2,
        "n": n,
        "mean": mean,
        "mag": mag,
        "var": s2 / n - mag ** 2,            # reference formula (== 0)
        "var_corrected": s2 / n - mean ** 2,  # meaningful variance
        "norm": jnp.sqrt(s2),
    }


def _resolve_dtype(name: Optional[str]):
    """Dtype of a precision-policy entry; None for absent OR an explicit
    "float32" — the two spellings must compile the identical program."""
    if name is None or str(name) == "float32":
        return None
    dt = jnp.dtype(name)
    if not jnp.issubdtype(dt, jnp.floating):
        raise ValueError(f"precision dtype must be floating, got {name!r}")
    return dt


def _cast_floats(tree: Any, dt) -> Any:
    """Cast every floating leaf to ``dt`` (ints/bools pass through —
    token ids and masks keep their layouts)."""
    return jax.tree.map(
        lambda x: x.astype(dt)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x, tree)


def build_client_update(task: BaseTask, client_opt_cfg,
                        hparams: ClientHParams) -> Callable:
    """Returns ``client_update(global_params, arrays, sample_mask, lr, rng)``
    -> ``(pseudo_grad, train_loss, num_samples, stats)``.

    ``arrays``: dict of ``[S, B, ...]`` feature arrays; ``sample_mask``:
    ``[S, B]``.  Pure and side-effect free: safe under vmap/shard_map/jit.
    """
    tx = make_optimizer(client_opt_cfg)
    freeze = hparams.freeze_layers
    # NOTE on rematerialization: each local step's grad is taken inside the
    # step scan, so wrapping task.loss in jax.checkpoint here would buy no
    # peak-HBM reduction (the step's own residuals still materialize).
    # Remat belongs INSIDE the model, per block — see model_config.remat
    # (models/ringlm.py, nn.remat around the transformer block).
    loss_fn = task.loss

    # precision policy: "float32"/None compile the exact legacy trace —
    # the cast helpers are built ONLY for a non-f32 dtype, so an absent
    # (or explicit f32) policy cannot perturb bit-identity
    pdt = _resolve_dtype(hparams.param_dtype)
    cdt = _resolve_dtype(hparams.compute_dtype)
    sdt = _resolve_dtype(hparams.stats_dtype) or jnp.float32
    if cdt is not None:
        base_loss = loss_fn

        def loss_fn(p, batch, rng, train):  # noqa: F811 - deliberate wrap
            # bf16 forward/backward: params + float features cast at the
            # loss boundary; autodiff transposes the cast, so grads come
            # back in the (f32 master) params dtype
            return base_loss(_cast_floats(p, cdt),
                             {k: _cast_floats(v, cdt)
                              for k, v in batch.items()}, rng, train)

    pallas_sgd = bool(hparams.pallas_apply)
    if pallas_sgd and not sgd_pallas_fusable(client_opt_cfg):
        raise ValueError(
            "megakernel.pallas_apply requires a plain SGD client "
            "optimizer (momentum ok; no nesterov/weight_decay) — got "
            f"type={client_opt_cfg.get('type', 'sgd')!r}")
    if pallas_sgd and hparams.updatable_layers is not None:
        raise ValueError(
            "megakernel.pallas_apply does not compose with "
            "updatable_layers: the flat fused kernel has no per-leaf "
            "freeze mask — drop one of them")
    sgd_mu = float(client_opt_cfg.get("momentum", 0.0) or 0.0)

    def client_update(global_params, arrays: Dict[str, jnp.ndarray],
                      sample_mask: jnp.ndarray, lr: jnp.ndarray,
                      rng: jax.Array, grad_offset=None):
        """``grad_offset`` (optional params-shaped pytree) is added to every
        inner step's gradient — the drift-correction hook used by SCAFFOLD's
        ``c - c_i`` control variate (``strategies/scaffold.py``); it
        participates in clipping like any other gradient term.  ``None``
        compiles to the plain path."""
        local_params = (jax.tree.map(lambda w: w.astype(pdt), global_params)
                        if pdt is not None else global_params)
        if pallas_sgd:
            # flat momentum carry + the trace-time unravel closure; the
            # optax state machinery is bypassed entirely
            from jax.flatten_util import ravel_pytree
            flat0, unravel = ravel_pytree(local_params)
            opt_state = jnp.zeros_like(flat0)
        else:
            opt_state = tx.init(local_params)
            opt_state.hyperparams["learning_rate"] = lr
        update_mask = (_updatable_mask(global_params,
                                       hparams.updatable_layers)
                       if hparams.updatable_layers is not None else None)

        def one_step(carry, xs):
            (params, opt_state, rng, loss_sum, s, s2, n_acc, wloss_acc,
             ns_acc) = carry
            batch_arrays, mask = xs
            batch = dict(batch_arrays)
            batch["sample_mask"] = mask
            rng, sub = jax.random.split(rng)
            (loss, _aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, sub, True)
            # offset + proximal + clip in one combining traversal
            # (optim/fused.py; bit-identical association to the legacy
            # three-pass spelling)
            grads = combine_grad_terms(
                grads, offset=grad_offset, prox_mu=hparams.fedprox_mu,
                params=params, global_params=global_params,
                max_norm=hparams.max_grad_norm)
            has_data = (jnp.sum(mask) > 0).astype(jnp.float32)
            # sufficient stats per batch (core/trainer.py:271-292)
            ds, ds2, dn = _suff_stats_of(grads)
            # the .astype(sdt) keeps the scan carry dtype stable under a
            # non-f32 stats policy; same-dtype casts compile to nothing,
            # so the f32 default trace is unchanged
            s = (s + has_data * ds).astype(sdt)
            s2 = (s2 + has_data * ds2).astype(sdt)
            n_acc = (n_acc + has_data * dn).astype(sdt)
            loss_sum = (loss_sum + has_data * loss).astype(sdt)
            # SAMPLE-weighted loss sum: loss is the batch's masked MEAN,
            # so loss * sum(mask) restores the per-sample sum — dividing
            # by (num_epochs * n_k) later gives a mean that is invariant
            # to how the samples were split into batches (q-FFL weights)
            wloss_acc = (wloss_acc + loss * jnp.sum(mask)).astype(sdt)
            # the task decides how the trainer COUNTS its samples
            # (reference core/trainer.py:397-405: rows by default, token
            # positions for mlm/frame-bearing batches) — this feeds
            # aggregation weights and DGA's train_loss/num_samples metric
            ns_acc = (ns_acc + has_data * _aux.get(
                "train_sample_count", jnp.sum(mask))).astype(sdt)
            if pallas_sgd:
                # megakernel tail: the whole optimizer step is one
                # fused pass over the flattened param vector, with the
                # all-padding no-op gate folded into the kernel
                from jax.flatten_util import ravel_pytree
                from ..ops.pallas_kernels import fused_sgd_apply
                new_p, opt_state = fused_sgd_apply(
                    ravel_pytree(params)[0], ravel_pytree(grads)[0],
                    opt_state, lr, sgd_mu, has_data)
                params = unravel(new_p)
            else:
                # optimizer transform + frozen-layer mask + apply + the
                # all-padding no-op pin (momentum included), apply+pin
                # fused into one traversal (optim/fused.py)
                params, opt_state = fused_apply(
                    tx, grads, opt_state, params,
                    update_mask=update_mask, has_data=has_data)
            return (params, opt_state, rng, loss_sum, s, s2, n_acc,
                    wloss_acc, ns_acc), None

        params = local_params
        loss_sum = jnp.zeros((), sdt)
        s = jnp.zeros((), sdt)
        s2 = jnp.zeros((), sdt)
        n_acc = jnp.zeros((), sdt)
        wloss_acc = jnp.zeros((), sdt)
        ns_acc = jnp.zeros((), sdt)
        carry = (params, opt_state, rng, loss_sum, s, s2, n_acc, wloss_acc,
                 ns_acc)
        if hparams.num_epochs <= 1 or not hparams.fused_epochs:
            # num_epochs == 1 is the exact historical trace either way;
            # the legacy unrolled path (megakernel.fused_epochs: false)
            # clones the scan body once per epoch — program size and
            # compile time grow linearly in num_epochs (the A/B arm)
            for _ in range(hparams.num_epochs):
                carry, _ = jax.lax.scan(one_step, carry,
                                        (arrays, sample_mask))
        else:
            # megakernel epoch fusion: ONE scan over the flattened
            # [num_epochs * steps] grid — the body is traced once, and
            # each step dynamic-slices its batch out of the resident
            # [S, B, ...] grids (an HBM-local gather, no host bytes)
            n_steps = sample_mask.shape[0]
            step_ids = (jnp.arange(hparams.num_epochs * n_steps,
                                   dtype=jnp.int32) % n_steps)

            def fused_step(carry, t):
                xs = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, t, 0, keepdims=False),
                    (arrays, sample_mask))
                return one_step(carry, xs)

            carry, _ = jax.lax.scan(fused_step, carry, step_ids)
        (params, opt_state, rng, loss_sum, s, s2, n_acc, wloss_acc,
         ns_acc) = carry

        pseudo_grad = jax.tree.map(lambda w0, w: w0 - w, global_params, params)
        if freeze:
            pseudo_grad = _freeze_layers(pseudo_grad, freeze)

        if hparams.stats_on_smooth_grad:
            # recompute stats on the pseudo-gradient (dga.py:104-108)
            s, s2, n = _suff_stats_of(pseudo_grad)
            stats = _derive_stats(s, s2, n)
        else:
            stats = _derive_stats(s, s2, n_acc)

        rows = jnp.sum(sample_mask)
        # per-SAMPLE (per-ROW) mean training loss, invariant to batch
        # partitioning (consumed by q-FFL's fairness weights,
        # strategies/qffl.py) — rows on purpose: wloss_acc accumulates
        # row-weighted batch means, regardless of the task's trainer
        # counting unit below
        stats["mean_sample_loss"] = wloss_acc / jnp.maximum(
            rows * hparams.num_epochs, 1.0)
        # ns_acc is the task's counting unit for this client — the
        # epoch loop re-counts per epoch like the reference
        # (train_desired_samples accumulates per epoch), so divide back
        num_samples = ns_acc / jnp.maximum(hparams.num_epochs, 1)
        return pseudo_grad, loss_sum, num_samples, stats

    return client_update


def build_mega_update(task: BaseTask, client_opt_cfg,
                      hparams: ClientHParams) -> Callable:
    """Cross-client megabatch lane scan (``server_config.megabatch``).

    Returns ``mega_update(global_params, arrays, sample_mask, client_ids,
    ptr, seg, lr, rng, init_rows=None, offset_rows=None, rng_salt=None)``
    -> the SAME per-row outputs as ``vmap(client_update)`` over the grid:
    ``(pseudo_grad [K,...], train_loss [K], num_samples [K], stats {[K]})``.

    Geometry: ``arrays``/``sample_mask`` are the bucket's shard-local
    ``[K, S, B, ...]`` grids; ``ptr``/``seg`` the ``[L, T]`` pointer tape
    from :func:`..data.batching.plan_megabatch`.  Instead of one vmap
    lane per client (K lanes, most steps padding), the scan runs ``L``
    lanes for ``T`` steps and every lane trains a CONCATENATION of small
    clients: at a slot whose segment id changes, the lane resets params /
    optimizer / rng / accumulators to the fresh client state
    (:func:`..optim.fused.segment_select`); at a segment's last slot the
    finished client's outputs scatter into its grid row of the output
    stacks.  Per-step math is ``one_step`` verbatim — same fused grad
    combine, same accumulator order, same no-op pinning — so each
    client's update is computed from exactly its own samples.

    rng identity contract (tests/test_megabatch.py): the per-client rng
    still folds on TRUE client ids, but the lane stream is COMPACT — it
    splits only on the client's real steps, while the vmap arm also
    splits on the grid's padded tail steps.  For ``num_epochs == 1`` the
    real steps consume the identical split prefix, so f32 results are
    BITWISE equal; for ``num_epochs > 1`` the streams diverge from epoch
    2 onward and rng-consuming losses (dropout) are only equal to
    MEGABATCH_FINAL_LOSS_RTOL — rng-free losses stay bitwise.

    Strategy hooks (``BaseStrategy.megabatch_passes``): ``init_rows``
    (``[K, n_flat]``) replaces the global start/anchor per client —
    FedBuff's stale history rows, personalization's local models;
    ``offset_rows`` is SCAFFOLD's flattened ``c - c_i`` drift correction;
    ``rng_salt`` reproduces a strategy's ``fold_in(rng_c, salt)``
    sub-stream.  Padding rows (``seg`` never points at them) come back
    with the exact values the vmap arm produces for masked-out rows.
    """
    tx = make_optimizer(client_opt_cfg)
    freeze = hparams.freeze_layers
    loss_fn = task.loss
    pdt = _resolve_dtype(hparams.param_dtype)
    cdt = _resolve_dtype(hparams.compute_dtype)
    sdt = _resolve_dtype(hparams.stats_dtype) or jnp.float32
    if cdt is not None:
        base_loss = loss_fn

        def loss_fn(p, batch, rng, train):  # noqa: F811 - deliberate wrap
            return base_loss(_cast_floats(p, cdt),
                             {k: _cast_floats(v, cdt)
                              for k, v in batch.items()}, rng, train)

    if hparams.pallas_apply:
        # engine/round.py refuses this combination up front; the raise
        # here keeps the builder safe standalone
        raise ValueError(
            "server_config.megabatch is incompatible with "
            "megakernel.pallas_apply: the flat fused kernel has no "
            "segment-reset lane — drop one of them")
    E = max(int(hparams.num_epochs), 1)

    def mega_update(global_params, arrays: Dict[str, jnp.ndarray],
                    sample_mask: jnp.ndarray, client_ids: jnp.ndarray,
                    ptr: jnp.ndarray, seg: jnp.ndarray, lr: jnp.ndarray,
                    rng: jax.Array, init_rows=None, offset_rows=None,
                    rng_salt=None):
        from jax.flatten_util import ravel_pytree
        K, S = int(sample_mask.shape[0]), int(sample_mask.shape[1])
        L = int(ptr.shape[0])
        _, unravel = ravel_pytree(global_params)
        update_mask = (_updatable_mask(global_params,
                                       hparams.updatable_layers)
                       if hparams.updatable_layers is not None else None)

        # flatten [K, S, ...] -> [K*S, ...]: a tape pointer is the
        # shard-local flat step index row*S + step, so each lane's batch
        # is ONE dynamic row gather out of the resident grids
        arrays_flat = {k: a.reshape((K * S,) + a.shape[2:])
                       for k, a in arrays.items()}
        mask_flat = sample_mask.reshape((K * S,) + sample_mask.shape[2:])

        def _fresh(seg_t):
            """(anchor, local-params) of the segment's client — the
            anchor is what prox/pseudo-grad measure against (the global,
            or the strategy's per-client start row)."""
            if init_rows is None:
                anchor = global_params
            else:
                anchor = unravel(init_rows[jnp.clip(seg_t, 0, K - 1)])
            lp = (jax.tree.map(lambda w: w.astype(pdt), anchor)
                  if pdt is not None else anchor)
            return anchor, lp

        def _fresh_rng(seg_t):
            cid = client_ids[jnp.clip(seg_t, 0, K - 1)]
            r = jax.random.fold_in(rng, cid)
            if rng_salt is not None:
                r = jax.random.fold_in(r, int(rng_salt))
            return r

        def lane_step(carry, xs):
            """ONE tape slot of ONE lane (vmapped over lanes).  Body is
            ``one_step`` with the segment reset in front and the harvest
            candidate behind."""
            (params, opt_state, rng_l, loss_sum, s, s2, n_acc, wloss_acc,
             ns_acc, rows_acc) = carry
            ptr_t, seg_t, start_t, _end_t = xs
            live = seg_t >= 0

            # --- segment start: this slot begins a NEW client
            anchor, fresh_lp = _fresh(seg_t)
            fresh_opt = tx.init(fresh_lp)
            fresh_opt.hyperparams["learning_rate"] = lr
            params = segment_select(start_t, fresh_lp, params)
            opt_state = segment_select(start_t, fresh_opt, opt_state)
            rng_l = jnp.where(start_t, _fresh_rng(seg_t), rng_l)
            zero = jnp.zeros((), sdt)
            loss_sum, s, s2, n_acc, wloss_acc, ns_acc, rows_acc = (
                jnp.where(start_t, zero, v)
                for v in (loss_sum, s, s2, n_acc, wloss_acc, ns_acc,
                          rows_acc))

            # --- one_step verbatim on the gathered batch
            batch = {k: a[ptr_t] for k, a in arrays_flat.items()}
            mask = jnp.where(live, mask_flat[ptr_t],
                             jnp.zeros_like(mask_flat[ptr_t]))
            batch["sample_mask"] = mask
            off = (None if offset_rows is None else
                   unravel(offset_rows[jnp.clip(seg_t, 0, K - 1)]))
            rng_l, sub = jax.random.split(rng_l)
            (loss, _aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, sub, True)
            grads = combine_grad_terms(
                grads, offset=off, prox_mu=hparams.fedprox_mu,
                params=params, global_params=anchor,
                max_norm=hparams.max_grad_norm)
            has_data = (jnp.sum(mask) > 0).astype(jnp.float32)
            ds, ds2, dn = _suff_stats_of(grads)
            s = (s + has_data * ds).astype(sdt)
            s2 = (s2 + has_data * ds2).astype(sdt)
            n_acc = (n_acc + has_data * dn).astype(sdt)
            loss_sum = (loss_sum + has_data * loss).astype(sdt)
            wloss_acc = (wloss_acc + loss * jnp.sum(mask)).astype(sdt)
            ns_acc = (ns_acc + has_data * _aux.get(
                "train_sample_count", jnp.sum(mask))).astype(sdt)
            # mask rows are 0/1 so the stepwise sum is exact in f32 —
            # rows_acc lands on rows * num_epochs bitwise, the vmap
            # arm's mean_sample_loss denominator
            rows_acc = (rows_acc + jnp.sum(mask)).astype(sdt)
            params, opt_state = fused_apply(
                tx, grads, opt_state, params,
                update_mask=update_mask, has_data=has_data)

            # --- harvest candidate (scattered only at segment ends)
            pg = jax.tree.map(lambda w0, w: w0 - w, anchor, params)
            if freeze:
                pg = _freeze_layers(pg, freeze)
            if hparams.stats_on_smooth_grad:
                hs, hs2, hn = _suff_stats_of(pg)
                stats = _derive_stats(hs, hs2, hn)
            else:
                stats = _derive_stats(s, s2, n_acc)
            stats["mean_sample_loss"] = wloss_acc / jnp.maximum(
                rows_acc, 1.0)
            num_samples = ns_acc / jnp.maximum(E, 1)
            new_carry = (params, opt_state, rng_l, loss_sum, s, s2,
                         n_acc, wloss_acc, ns_acc, rows_acc)
            return new_carry, (pg, loss_sum, num_samples, stats)

        def scan_body(carry, xs):
            lane_carry, (pg_stack, tl_stack, ns_stack, stats_stack) = carry
            ptr_t, seg_t, start_t, end_t = xs
            new_lane_carry, cand = jax.vmap(lane_step)(
                lane_carry, (ptr_t, seg_t, start_t, end_t))
            # each finished segment owns exactly one grid row, so the
            # lane->row scatter has unique in-bounds targets; idle/non-
            # end lanes aim at row K and drop
            idx = jnp.where(end_t & (seg_t >= 0), seg_t, K)
            pg_stack = jax.tree.map(
                lambda o, v: o.at[idx].set(v, mode="drop"),
                pg_stack, cand[0])
            tl_stack = tl_stack.at[idx].set(cand[1], mode="drop")
            ns_stack = ns_stack.at[idx].set(cand[2], mode="drop")
            stats_stack = jax.tree.map(
                lambda o, v: o.at[idx].set(v, mode="drop"),
                stats_stack, cand[3])
            return (new_lane_carry,
                    (pg_stack, tl_stack, ns_stack, stats_stack)), None

        # --- segment boundaries, derived from the tape in-trace
        ptr_T, seg_T = ptr.T, seg.T                      # [T, L]
        fence = jnp.full((1, L), -2, seg.dtype)
        start_T = seg_T != jnp.concatenate([fence, seg_T[:-1]])
        end_T = seg_T != jnp.concatenate([seg_T[1:], fence])

        # --- output stacks start at the vmap arm's PADDING-ROW values,
        # so rows no segment ends on (client_mask == 0 rows) come back
        # identical to a grid row that ran all-masked steps
        def _pg0_of(tree):
            if pdt is None:
                out = jax.tree.map(jnp.zeros_like, tree)
            else:
                out = jax.tree.map(lambda w: w - w.astype(pdt), tree)
            return _freeze_layers(out, freeze) if freeze else out

        if init_rows is None:
            pg0_one = _pg0_of(global_params)
            pg0 = jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (K,) + x.shape),
                pg0_one)
        else:
            pg0 = jax.vmap(lambda r: _pg0_of(unravel(r)))(init_rows)
        if hparams.stats_on_smooth_grad:
            stats0 = jax.vmap(
                lambda t: _derive_stats(*_suff_stats_of(t)))(pg0)
        else:
            z_k = jnp.zeros((K,), sdt)
            stats0 = _derive_stats(z_k, z_k, z_k)
        stats0 = dict(stats0)
        stats0["mean_sample_loss"] = jnp.zeros((K,), sdt)
        tl0 = jnp.zeros((K,), sdt)
        ns0 = jnp.zeros((K,), sdt)

        # --- initial lane carry (slot 0 always starts a segment, so
        # these are reset before any math touches them)
        lp0_one = (jax.tree.map(lambda w: w.astype(pdt), global_params)
                   if pdt is not None else global_params)
        opt0_one = tx.init(lp0_one)
        opt0_one.hyperparams["learning_rate"] = lr
        bcast = lambda x: jnp.broadcast_to(  # noqa: E731
            jnp.asarray(x)[None], (L,) + jnp.asarray(x).shape)
        lane_params0 = jax.tree.map(bcast, lp0_one)
        lane_opt0 = jax.tree.map(bcast, opt0_one)
        rng0 = bcast(rng)
        z_l = jnp.zeros((L,), sdt)
        lane_carry0 = (lane_params0, lane_opt0, rng0, z_l, z_l, z_l, z_l,
                       z_l, z_l, z_l)

        (_, outs), _ = jax.lax.scan(
            scan_body, (lane_carry0, (pg0, tl0, ns0, stats0)),
            (ptr_T, seg_T, start_T, end_T))
        return outs

    return mega_update


def _updatable_mask(params, patterns) -> Any:
    """Per-leaf PYTHON bools from the updatable_layers regex allowlist
    (names are '.'-joined like torch's named_parameters; patterns are
    start-anchored via re.match, matching the reference).  Static at
    trace time, so frozen updates compile to nothing.  Shared by the
    per-client and megabatch update builders."""
    import logging
    import re

    from ..utils.logging import print_rank
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    keeps = []
    for path, leaf in flat:
        name = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        keep = any(re.match(pat, name) for pat in patterns)
        print_rank(("updating " if keep else "freezing ") + name,
                   loglevel=logging.DEBUG)
        keeps.append(bool(keep))
    return jax.tree_util.tree_unflatten(treedef, keeps)


def _freeze_layers(tree: Any, freeze: Tuple[str, ...]) -> Any:
    """Zero pseudo-gradients of frozen layers by path-name match
    (reference zeroes ``p.grad`` for names in ``freeze_layer``,
    ``core/client.py:306-307``, ``core/strategies/fedavg.py:83-88``)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)
    paths_leaves, treedef = flat
    out = []
    for path, leaf in paths_leaves:
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        if any(f in name for f in freeze):
            out.append(jnp.zeros_like(leaf))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)
