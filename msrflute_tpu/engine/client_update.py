"""Per-client local training as a pure jittable function.

Parity target: reference ``Client.process_round`` + ``Trainer``
(``core/client.py:226-511``, ``core/trainer.py:200-687``).  Semantics
preserved exactly (SURVEY.md §7):

- model reset per client: local params start from the server's globals
  (``core/client.py:294-302``) — here simply the function argument;
- fresh optimizer per client with the server-dictated LR
  (``core/client.py:309-312``) — optax init inside the function;
- per-batch loss -> grad -> clip -> stats -> step
  (``core/trainer.py:341-414``) — a ``lax.scan`` over the static step grid;
- ``desired_max_samples`` early stop (``core/trainer.py:363-364``) — encoded
  in the batch packing (zero-mask beyond the cap), with all-padding steps
  gated so they change nothing;
- FedProx proximal term ``mu * (w - w_global)`` added to gradients
  (``core/trainer.py:416-501``);
- pseudo-gradient = w_server - w_trained (``core/client.py:380-383``);
- gradient sufficient stats accumulated per batch
  (``core/trainer.py:263-312``): ``sum``, ``sq_sum``, ``n``, and derived
  ``mean = sum/n``, ``mag = sqrt(sq_sum/n)``, ``norm = sqrt(sq_sum)``.
  NOTE the reference computes ``var = sq_sum/n - mag**2`` which is
  identically zero (``core/trainer.py:301``); we keep that key for parity
  but also expose the statistically meaningful ``var_corrected =
  sq_sum/n - mean**2``.
- per-layer freezing (``core/client.py:306-307``): frozen layers get zero
  pseudo-gradient, equivalent to the reference's zeroed ``p.grad``.

This function is ``vmap``-ed over the round's clients and ``shard_map``-ed
over the mesh by :mod:`msrflute_tpu.engine.round` — the role FLUTE's Worker
processes play (``core/federated.py:482-632``), with no RPC.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..models.base import BaseTask
from ..optim import make_optimizer


@dataclass(frozen=True)
class ClientHParams:
    """Static client-update hyperparameters (compiled into the program)."""

    max_grad_norm: Optional[float] = None       # core/trainer clip
    fedprox_mu: float = 0.0                     # FedProx proximal weight
    num_epochs: int = 1                         # local epochs per round
    stats_on_smooth_grad: bool = True           # dga.py:104-108
    freeze_layers: Tuple[str, ...] = ()         # core/client.py:306-307
    #: regex allowlist — when set, ONLY matching layers move; the rest are
    #: frozen at every inner step, like the reference's per-param lr=0
    #: (set_component_wise_lr, core/trainer.py:725-751)
    updatable_layers: Optional[Tuple[str, ...]] = None


def _global_norm(tree: Any) -> jnp.ndarray:
    return optax.global_norm(tree)


def _clip_by_global_norm(tree: Any, max_norm: float) -> Any:
    norm = _global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: g * scale, tree)


def _suff_stats_of(tree: Any) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    leaves = jax.tree.leaves(tree)
    s = sum(jnp.sum(g) for g in leaves)
    s2 = sum(jnp.sum(g * g) for g in leaves)
    n = float(sum(g.size for g in leaves))
    return s, s2, jnp.asarray(n)


def _derive_stats(s, s2, n) -> Dict[str, jnp.ndarray]:
    n = jnp.maximum(n, 1.0)
    mean = s / n
    mag = jnp.sqrt(s2 / n)
    return {
        "sum": s,
        "sq_sum": s2,
        "n": n,
        "mean": mean,
        "mag": mag,
        "var": s2 / n - mag ** 2,            # reference formula (== 0)
        "var_corrected": s2 / n - mean ** 2,  # meaningful variance
        "norm": jnp.sqrt(s2),
    }


def build_client_update(task: BaseTask, client_opt_cfg,
                        hparams: ClientHParams) -> Callable:
    """Returns ``client_update(global_params, arrays, sample_mask, lr, rng)``
    -> ``(pseudo_grad, train_loss, num_samples, stats)``.

    ``arrays``: dict of ``[S, B, ...]`` feature arrays; ``sample_mask``:
    ``[S, B]``.  Pure and side-effect free: safe under vmap/shard_map/jit.
    """
    tx = make_optimizer(client_opt_cfg)
    freeze = hparams.freeze_layers
    # NOTE on rematerialization: each local step's grad is taken inside the
    # step scan, so wrapping task.loss in jax.checkpoint here would buy no
    # peak-HBM reduction (the step's own residuals still materialize).
    # Remat belongs INSIDE the model, per block — see model_config.remat
    # (models/ringlm.py, nn.remat around the transformer block).
    loss_fn = task.loss

    def _updatable_mask(params):
        """Per-leaf PYTHON bools from the updatable_layers regex allowlist
        (names are '.'-joined like torch's named_parameters; patterns are
        start-anchored via re.match, matching the reference).  Static at
        trace time, so frozen updates compile to nothing."""
        import logging
        import re

        from ..utils.logging import print_rank
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        keeps = []
        for path, leaf in flat:
            name = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path)
            keep = any(re.match(pat, name)
                       for pat in hparams.updatable_layers)
            print_rank(("updating " if keep else "freezing ") + name,
                       loglevel=logging.DEBUG)
            keeps.append(bool(keep))
        return jax.tree_util.tree_unflatten(treedef, keeps)

    def client_update(global_params, arrays: Dict[str, jnp.ndarray],
                      sample_mask: jnp.ndarray, lr: jnp.ndarray,
                      rng: jax.Array, grad_offset=None):
        """``grad_offset`` (optional params-shaped pytree) is added to every
        inner step's gradient — the drift-correction hook used by SCAFFOLD's
        ``c - c_i`` control variate (``strategies/scaffold.py``); it
        participates in clipping like any other gradient term.  ``None``
        compiles to the plain path."""
        opt_state = tx.init(global_params)
        opt_state.hyperparams["learning_rate"] = lr
        update_mask = (_updatable_mask(global_params)
                       if hparams.updatable_layers is not None else None)

        def one_step(carry, xs):
            (params, opt_state, rng, loss_sum, s, s2, n_acc, wloss_acc,
             ns_acc) = carry
            batch_arrays, mask = xs
            batch = dict(batch_arrays)
            batch["sample_mask"] = mask
            rng, sub = jax.random.split(rng)
            (loss, _aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, sub, True)
            if grad_offset is not None:
                grads = jax.tree.map(lambda g, o: g + o, grads, grad_offset)
            if hparams.fedprox_mu > 0.0:
                grads = jax.tree.map(
                    lambda g, w, w0: g + hparams.fedprox_mu * (w - w0),
                    grads, params, global_params)
            if hparams.max_grad_norm is not None:
                grads = _clip_by_global_norm(grads, hparams.max_grad_norm)
            has_data = (jnp.sum(mask) > 0).astype(jnp.float32)
            # sufficient stats per batch (core/trainer.py:271-292)
            ds, ds2, dn = _suff_stats_of(grads)
            s = s + has_data * ds
            s2 = s2 + has_data * ds2
            n_acc = n_acc + has_data * dn
            loss_sum = loss_sum + has_data * loss
            # SAMPLE-weighted loss sum: loss is the batch's masked MEAN,
            # so loss * sum(mask) restores the per-sample sum — dividing
            # by (num_epochs * n_k) later gives a mean that is invariant
            # to how the samples were split into batches (q-FFL weights)
            wloss_acc = wloss_acc + loss * jnp.sum(mask)
            # the task decides how the trainer COUNTS its samples
            # (reference core/trainer.py:397-405: rows by default, token
            # positions for mlm/frame-bearing batches) — this feeds
            # aggregation weights and DGA's train_loss/num_samples metric
            ns_acc = ns_acc + has_data * _aux.get(
                "train_sample_count", jnp.sum(mask))
            updates, new_opt = tx.update(grads, opt_state, params)
            if update_mask is not None:
                # frozen layers never move at ANY inner step (the per-param
                # lr=0 semantics of the reference; momentum state still
                # accumulates, exactly like torch SGD with lr=0); the mask
                # is static, so frozen leaves are zero constants in XLA
                updates = jax.tree.map(
                    lambda u, keep: u if keep else jnp.zeros_like(u),
                    updates, update_mask)
            new_params = optax.apply_updates(params, updates)
            # all-padding steps must be no-ops (momentum included)
            params = jax.tree.map(
                lambda new, old: jnp.where(has_data > 0, new, old),
                new_params, params)
            opt_state = jax.tree.map(
                lambda new, old: jnp.where(has_data > 0, new, old),
                new_opt, opt_state)
            return (params, opt_state, rng, loss_sum, s, s2, n_acc,
                    wloss_acc, ns_acc), None

        params = global_params
        loss_sum = jnp.zeros(())
        s = jnp.zeros(())
        s2 = jnp.zeros(())
        n_acc = jnp.zeros(())
        wloss_acc = jnp.zeros(())
        ns_acc = jnp.zeros(())
        carry = (params, opt_state, rng, loss_sum, s, s2, n_acc, wloss_acc,
                 ns_acc)
        for _ in range(hparams.num_epochs):
            carry, _ = jax.lax.scan(carry_step := one_step, carry,
                                    (arrays, sample_mask))
        (params, opt_state, rng, loss_sum, s, s2, n_acc, wloss_acc,
         ns_acc) = carry

        pseudo_grad = jax.tree.map(lambda w0, w: w0 - w, global_params, params)
        if freeze:
            pseudo_grad = _freeze_layers(pseudo_grad, freeze)

        if hparams.stats_on_smooth_grad:
            # recompute stats on the pseudo-gradient (dga.py:104-108)
            s, s2, n = _suff_stats_of(pseudo_grad)
            stats = _derive_stats(s, s2, n)
        else:
            stats = _derive_stats(s, s2, n_acc)

        rows = jnp.sum(sample_mask)
        # per-SAMPLE (per-ROW) mean training loss, invariant to batch
        # partitioning (consumed by q-FFL's fairness weights,
        # strategies/qffl.py) — rows on purpose: wloss_acc accumulates
        # row-weighted batch means, regardless of the task's trainer
        # counting unit below
        stats["mean_sample_loss"] = wloss_acc / jnp.maximum(
            rows * hparams.num_epochs, 1.0)
        # ns_acc is the task's counting unit for this client — the
        # epoch loop re-counts per epoch like the reference
        # (train_desired_samples accumulates per epoch), so divide back
        num_samples = ns_acc / jnp.maximum(hparams.num_epochs, 1)
        return pseudo_grad, loss_sum, num_samples, stats

    return client_update


def _freeze_layers(tree: Any, freeze: Tuple[str, ...]) -> Any:
    """Zero pseudo-gradients of frozen layers by path-name match
    (reference zeroes ``p.grad`` for names in ``freeze_layer``,
    ``core/client.py:306-307``, ``core/strategies/fedavg.py:83-88``)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)
    paths_leaves, treedef = flat
    out = []
    for path, leaf in paths_leaves:
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        if any(f in name for f in freeze):
            out.append(jnp.zeros_like(leaf))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)
