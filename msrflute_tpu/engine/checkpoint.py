"""Checkpoint / resume.

Parity target: reference §5.4 — tar checkpoints of
model/optimizer/lr-scheduler state (``core/trainer.py:753-775``),
``latest_model`` every round + ``epoch<i>`` and best-model copies every
``model_backup_freq`` (``core/server.py:530-558``), ``status_log.json``
(``core/server.py:477-490``), resume (``core/server.py:183-204``), and
fallback-to-best (``core/server.py:561-578``).

Format: flax msgpack serialization of the full :class:`ServerState` pytree
(+ a sidecar JSON with round/best-metric bookkeeping).  Saves run under
the bounded retry-with-backoff policy (``server_config.checkpoint_retry``,
generalizing the reference's fixed 3-retry wrapper,
``utils/utils.py:348-359``) with crc32 integrity sidecars and two-slot
fallback on load — see :mod:`msrflute_tpu.resilience.integrity`.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
from flax import serialization

from ..resilience.integrity import (CheckpointCorruptionError,
                                    FailureEscalator, RetryPolicy,
                                    blob_checksum, run_with_retry,
                                    tree_checksum, verify_blob,
                                    write_sidecar)
from ..telemetry import NULL_SPAN, emit_event
from ..utils.io import update_json_log
from ..utils.logging import print_rank
from .round import ServerState

LATEST = "latest_model.msgpack"
#: previous-generation latest (two-slot msgpack scheme): rotated into
#: place on every latest save, so a corrupted/torn ``latest_model`` falls
#: back one round instead of losing the run
LATEST_PREV = LATEST + ".prev"
STATUS_LOG = "status_log.json"


def _payload(state: ServerState) -> dict:
    """The one checkpointed dict, shared by every backend — add new
    ServerState fields HERE (and in :func:`_merge`) only."""
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "strategy_state": state.strategy_state,
        "round": state.round,
    }


def _merge(template: ServerState, restored: dict) -> ServerState:
    """Restore typed pytrees (optax namedtuples etc.) from a plain
    state-dict by merging onto the RAW template payload."""
    merged = serialization.from_state_dict(
        _payload(template), restored)
    return ServerState(
        params=merged["params"],
        opt_state=merged["opt_state"],
        strategy_state=merged["strategy_state"],
        round=int(restored.get("round", 0)),
    )


def _state_to_bytes(state: ServerState) -> bytes:
    return serialization.msgpack_serialize(
        serialization.to_state_dict(jax.device_get(_payload(state))))


def _state_from_bytes(data: bytes, template: ServerState) -> ServerState:
    return _merge(template, serialization.msgpack_restore(data))


def load_pretrained_params(path: str, template_params,
                           data_path: Optional[str] = None):
    """Load model params from a checkpoint file for warm-starting training
    (reference ``model_config.pretrained_model_path``, ``core/config.py:93``;
    relative paths resolve against ``data_path``, ``core/config.py:744-745``).

    Accepts a full :class:`ServerState` dump from EITHER backend (msgpack
    file or orbax checkpoint directory — anything this module wrote:
    ``latest``/``epoch<i>``/``best_val_*``) or a bare params-pytree
    msgpack; only the params are taken.
    """
    if not os.path.isabs(path) and not os.path.exists(path) and data_path:
        path = os.path.join(data_path, path)
    if os.path.isdir(path):
        # orbax checkpoint directory
        import orbax.checkpoint as ocp
        with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as cp:
            restored = cp.restore(os.path.abspath(path))
    else:
        with open(path, "rb") as fh:
            restored = serialization.msgpack_restore(fh.read())
    target = jax.device_get(template_params)
    if isinstance(restored, dict) and "params" in restored:
        restored = restored["params"]
    return serialization.from_state_dict(target, restored)


class CheckpointManager:
    """latest/every-N/best checkpoint policy + status log.

    Backends: ``msgpack`` (default; one flat file, synchronous) or
    ``orbax`` (``server_config.checkpoint_backend: orbax``) — async saves
    via ``orbax.checkpoint.AsyncCheckpointer``, so serialization/IO of the
    previous round's state overlaps the next rounds' device compute (the
    TPU-framework norm for big models; the reference's torch.save has no
    async path).

    Async durability contract: a round's checkpoint becomes the committed
    resume anchor at the NEXT save/load/wait (two-slot + pointer for
    ``latest``, tmp-dir + rename for ``best``), so a hard crash can lose
    at most the one most recent round — the inherent async window.

    Resilience contract (resilience/integrity.py): every physical write
    retries under the bounded backoff policy
    (``server_config.checkpoint_retry``); a fully-failed save warns and
    training continues UNTIL ``escalation_threshold`` consecutive
    failures, which abort via :class:`CheckpointEscalationError`.  Saves
    record crc32 checksums (``.sum`` sidecars / the orbax pointer);
    loads verify them and fall back to the surviving slot
    (``latest_model.msgpack.prev`` / the other orbax slot) on
    corruption, logging a recovery event.
    """

    def __init__(self, model_dir: str, backup_freq: int = 100,
                 backend: str = "msgpack", async_latest: bool = False,
                 retry: Optional[RetryPolicy] = None,
                 io_fault: Optional[Callable[[], None]] = None):
        self.model_dir = model_dir
        self.backup_freq = max(int(backup_freq), 1)
        if backend not in ("msgpack", "orbax"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.backend = backend
        #: bounded retry + backoff for transient IO failures
        #: (``server_config.checkpoint_retry``) and the consecutive-
        #: failure escalation that aborts instead of training
        #: uncheckpointed forever
        self.retry = retry or RetryPolicy()
        self.escalator = FailureEscalator(self.retry.escalation_threshold)
        #: optional flutescope scope (assigned by the server): writer-
        #: thread spans + structured recovery/fault events; None keeps
        #: every emission a metrics-stream-only record or a no-op
        self.telemetry = None
        #: chaos hook: called at the start of every physical write
        #: attempt; raises to inject a deterministic IO fault — wrapped
        #: so every injected fault leaves a structured event record
        #: (tools/chaos_smoke.py asserts these reach the trace)
        base_fault = io_fault or (lambda: None)

        def _fault_probe():
            try:
                base_fault()
            except Exception:
                emit_event(self.telemetry, "ckpt_io_fault")
                raise

        self._io_fault = _fault_probe
        #: load-time integrity/fallback observability: one dict per
        #: recovery (corrupted slot skipped, backup slot used, ...)
        self.recovery_events: List[Dict[str, str]] = []
        self._orbax = None
        self._pending_slot = None
        self._pending_renames = []  # [(tmp_dir, final_dir)] after async save
        if backend == "orbax":
            import orbax.checkpoint as ocp
            self._ocp = ocp
            self._orbax = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # msgpack async-latest: per-round ``latest`` saves hand a DEVICE
        # snapshot to a writer thread, so the device->host transfer and
        # the disk write overlap the next rounds' compute (the per-round
        # sync fetch is the faithful-mode fullrun's dominant cost on a
        # remote-attached chip; SURVEY §7 explicitly budgets for async
        # checkpointing).  Same durability contract as the orbax path: a
        # hard crash can lose at most the in-flight save.
        self.async_latest = bool(async_latest) and backend == "msgpack"
        self._mp_cond = threading.Condition()
        self._mp_mailbox = None   # single-slot device snapshot (see _mp_submit)
        self._mp_busy = False
        self._mp_worker = None
        os.makedirs(model_dir, exist_ok=True)

    # -- orbax helpers -------------------------------------------------
    _LATEST_SLOTS = ("latest_model.orbax.a", "latest_model.orbax.b")
    _LATEST_PTR = "latest_model.orbax.ptr"

    def _orbax_path(self, name: str) -> str:
        # orbax checkpoints are directories; keep the msgpack names with a
        # .orbax suffix so both backends can coexist in one model_dir
        return os.path.join(os.path.abspath(self.model_dir),
                            name.replace(".msgpack", ".orbax"))

    def _recover(self, event: str, path: str) -> None:
        """Record + log one integrity-recovery event (corrupt slot
        skipped, fallback slot used) — also a structured record in the
        metrics stream (and the trace, when telemetry is on) instead of
        a log-line-only breadcrumb."""
        self.recovery_events.append({"event": event, "path": path})
        emit_event(self.telemetry, "checkpoint_recovery", detail=event,
                   path=path)
        print_rank(f"checkpoint recovery: {event} ({path})",
                   loglevel=logging.WARNING)

    def _orbax_save(self, path: str, state: ServerState) -> None:
        """Issue one async save, with the bounded-retry policy on the
        submit itself (actual IO failures surface later in ``_drain``);
        a fully-failed submit counts toward the failure escalation."""
        payload = serialization.to_state_dict(_payload(state))
        self._drain()  # one in-flight save at a time + commit renames

        def _submit():
            self._io_fault()
            self._orbax.save(path, args=self._ocp.args.StandardSave(payload),
                             force=True)

        if run_with_retry(_submit, self.retry,
                          what=f"orbax save {os.path.basename(path)}"):
            self.escalator.record_success()
        else:
            self.escalator.record_failure(f"orbax save {path}")
        self.escalator.check()

    def _drain(self) -> None:
        """Finish the in-flight save (tolerating failure, which counts
        toward the escalation threshold) and perform any deferred
        directory renames.  Failed renames are RE-QUEUED for the next
        drain — a transient NFS error must not strand a completed save
        in its tmp dir forever."""
        try:
            self._orbax.wait_until_finished()
        except (KeyboardInterrupt, SystemExit):
            # fatal signals propagate — a Ctrl-C mid-wait must kill the
            # run, not be logged away as a failed save
            raise
        except Exception as exc:
            print_rank(f"async checkpoint save failed: {exc!r}",
                       loglevel=logging.WARNING)
            self._pending_slot = None
            self.escalator.record_failure("orbax async save")
            # pending renames are NOT cleared: they reference tmp dirs of
            # earlier, possibly successful saves — the isdir() guard below
            # skips any whose save really did fail
            return
        survivors = []
        for tmp, final in self._pending_renames:
            if not os.path.isdir(tmp):
                continue
            old = final + ".old"
            try:
                # a crash between the renames below can leave a stale .old
                # behind; clear it or os.rename onto it raises ENOTEMPTY
                # forever after
                shutil.rmtree(old, ignore_errors=True)
                if os.path.isdir(final):
                    os.rename(final, old)
                os.rename(tmp, final)
                shutil.rmtree(old, ignore_errors=True)
            except OSError as exc:
                print_rank(f"checkpoint rename {tmp} -> {final} failed: "
                           f"{exc!r}; re-queued for the next drain",
                           loglevel=logging.WARNING)
                survivors.append((tmp, final))
        self._pending_renames = survivors

    def _orbax_load(self, path: str,
                    template: ServerState) -> Optional[ServerState]:
        if not os.path.isdir(path):
            return None
        self._orbax.wait_until_finished()
        target = serialization.to_state_dict(jax.device_get(
            _payload(template)))
        restored = self._orbax.restore(
            path, args=self._ocp.args.StandardRestore(target))
        return _merge(template, restored)

    def _commit_pending_latest(self) -> None:
        """Point the latest-pointer at the slot whose async save has now
        finished (two-slot scheme: the previous committed slot stays valid
        through the entire save window, so a crash mid-save never loses
        the resume anchor — the async analogue of tmp+os.replace).  The
        pointer records the slot's tree checksum, verified at load."""
        if self._pending_slot is None:
            self._drain()
            return
        slot = self._pending_slot
        self._pending_slot = None
        self._drain()
        slot_dir = self._orbax_path(slot)
        if not os.path.isdir(slot_dir):
            return  # the save failed; keep pointing at the old slot
        self.escalator.record_success()
        ptr = os.path.join(self.model_dir, self._LATEST_PTR)
        tmp = ptr + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"slot": slot, "crc32": tree_checksum(slot_dir)}, fh)
        os.replace(tmp, ptr)

    def _latest_ptr(self) -> Optional[Dict[str, Any]]:
        """Parse the latest pointer: new JSON form ``{"slot", "crc32"}``
        or the legacy bare slot-name string (no checksum -> no
        verification, so pre-integrity checkpoints keep loading)."""
        ptr = os.path.join(self.model_dir, self._LATEST_PTR)
        if not os.path.exists(ptr):
            return None
        with open(ptr) as fh:
            text = fh.read().strip()
        if not text:
            return None
        try:
            parsed = json.loads(text)
            if isinstance(parsed, dict) and "slot" in parsed:
                return parsed
        except json.JSONDecodeError:
            pass
        return {"slot": text, "crc32": None}

    def _latest_slot(self) -> Optional[str]:
        parsed = self._latest_ptr()
        return None if parsed is None else parsed.get("slot")

    def wait(self) -> None:
        """Block until pending async saves are durable (call before reading
        checkpoint files externally or at process exit)."""
        if self._orbax is not None:
            self._commit_pending_latest()
        self._mp_wait()

    # -- msgpack async-latest writer ------------------------------------
    def _mp_wait(self) -> None:
        if self._mp_worker is None:
            return
        with self._mp_cond:
            while self._mp_mailbox is not None or self._mp_busy:
                self._mp_cond.wait()
        # surface the writer thread's accumulated failures HERE, on the
        # training thread — an exception raised inside the daemon writer
        # would vanish and the run would train uncheckpointed forever
        self.escalator.check()

    def _mp_loop(self) -> None:
        path = os.path.join(self.model_dir, LATEST)
        while True:
            with self._mp_cond:
                while self._mp_mailbox is None:
                    self._mp_cond.wait()
                snap = self._mp_mailbox
                self._mp_mailbox = None
                self._mp_busy = True
            try:
                # flutescope: the async writer's fetch+serialize+write
                # appears on ITS OWN thread track in the trace — the
                # direct visual of checkpoint IO overlapping (or
                # stalling) device rounds
                with (self.telemetry.span("ckpt_async_write")
                      if self.telemetry is not None else NULL_SPAN):
                    blob = serialization.msgpack_serialize(
                        serialization.to_state_dict(jax.device_get(snap)))
                    del snap  # release the HBM snapshot before the write
                    # _write_blob already retries + counts the failure
                    # toward escalation; the abort itself surfaces at the
                    # training thread's next submit/wait (escalator.check
                    # there), never out of this daemon thread where it
                    # would vanish
                    self._write_blob(path, blob, keep_prev=True)
                    del blob
            except (KeyboardInterrupt, SystemExit):
                raise  # fatal signals must not be logged away
            except Exception as exc:  # never kill training from the writer
                print_rank(f"async latest save failed: {exc!r}",
                           loglevel=logging.WARNING)
                self.escalator.record_failure("async latest serialize")
            finally:
                with self._mp_cond:
                    self._mp_busy = False
                    self._mp_cond.notify_all()

    def _mp_submit(self, state: ServerState) -> None:
        # single-slot, not latest-wins: wait for the in-flight save first,
        # so the on-disk latest can lag the status log by AT MOST the one
        # in-flight round — the same durability window the orbax path
        # documents.  (Latest-wins would let a slow disk stack unbounded
        # skew between latest_model and status_log.json, and resume pairs
        # the two.)  The wait also bounds snapshot HBM to one extra copy.
        self.escalator.check()  # abort on the training thread, not the writer
        if self._mp_worker is None:
            self._mp_worker = threading.Thread(
                target=self._mp_loop, name="ckpt-latest-writer", daemon=True)
            self._mp_worker.start()
        with self._mp_cond:
            while self._mp_mailbox is not None or self._mp_busy:
                self._mp_cond.wait()
        # device-side copy: the round step donates the live param/opt
        # buffers, so the snapshot must be arrays nothing else consumes.
        # The copies are enqueued on the device stream BEFORE any later
        # donating program, so they read the pre-donation values; the
        # writer thread's device_get then overlaps the next rounds.
        # Host numpy leaves (e.g. mutable strategy_state arrays) are
        # np.copy'd for the same reason: a by-reference share would let
        # an in-place mutation on the training thread reach the writer's
        # serialize mid-flight and persist a torn value.
        import jax.numpy as jnp
        import numpy as _np
        snap = jax.tree.map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array)
            else (_np.copy(x) if isinstance(x, _np.ndarray) else x),
            _payload(state))
        with self._mp_cond:
            self._mp_mailbox = snap
            self._mp_cond.notify()

    # -- save ----------------------------------------------------------
    def save_latest(self, state: ServerState) -> None:
        if self.backend == "orbax":
            self._commit_pending_latest()
            committed = self._latest_slot()
            slot = (self._LATEST_SLOTS[1]
                    if committed == self._LATEST_SLOTS[0]
                    else self._LATEST_SLOTS[0])
            self._orbax_save(self._orbax_path(slot), state)
            self._pending_slot = slot
            return
        if self.async_latest:
            self._mp_submit(state)
            return
        self._write(os.path.join(self.model_dir, LATEST), state,
                    keep_prev=True)

    def backup(self, state: ServerState, round_no: int,
               best_names: Tuple[str, ...] = ()) -> None:
        """Every ``backup_freq`` rounds: ``epoch<i>`` copy + snapshots of the
        best-model files (reference ``core/server.py:530-558``)."""
        if round_no % self.backup_freq:
            return
        if self.backend == "orbax":
            self.wait()  # copies must see complete checkpoints
            slot = self._latest_slot()
            src = self._orbax_path(slot) if slot else ""
            if src and os.path.isdir(src):
                dst = self._orbax_path(f"epoch{round_no}.orbax")
                if not os.path.isdir(dst):
                    shutil.copytree(src, dst)
            for name in best_names:
                best = self._orbax_path(f"best_val_{name}_model.orbax")
                dst = self._orbax_path(
                    f"best_val_{name}_model_epoch{round_no}.orbax")
                if os.path.isdir(best) and not os.path.isdir(dst):
                    shutil.copytree(best, dst)
            return
        self._mp_wait()  # the epoch copy must see the newest latest file
        src = os.path.join(self.model_dir, LATEST)
        if os.path.exists(src):
            shutil.copyfile(src, os.path.join(self.model_dir,
                                              f"epoch{round_no}.msgpack"))
        for name in best_names:
            best = os.path.join(self.model_dir, f"best_val_{name}_model.msgpack")
            if os.path.exists(best):
                shutil.copyfile(best, os.path.join(
                    self.model_dir, f"best_val_{name}_model_epoch{round_no}.msgpack"))

    def save_best(self, state: ServerState, metric_name: str) -> None:
        """Best-val checkpoint on improvement (reference
        ``core/evaluation.py:103-109``)."""
        if self.backend == "orbax":
            # async save to a .new dir; the rename into place happens at
            # the next drain, with the previous best parked at .old until
            # the swap completes — no moment without a readable best
            final = self._orbax_path(f"best_val_{metric_name}_model.orbax")
            tmp = final + ".new"
            shutil.rmtree(tmp, ignore_errors=True)
            self._orbax_save(tmp, state)
            self._pending_renames.append((tmp, final))
            return
        self._write(os.path.join(
            self.model_dir, f"best_val_{metric_name}_model.msgpack"), state)

    def _write_blob(self, path: str, blob: bytes,
                    keep_prev: bool = False) -> bool:
        """Atomic tmp-write + rename under the bounded-retry policy —
        THE write recipe, shared by the sync and async-latest paths.
        Records a crc32 sidecar (verified at load) and, for the latest
        slot (``keep_prev``), rotates the previous generation to
        ``.prev`` first so corruption always has a fallback.  Returns
        success; the failure is already counted toward escalation (the
        CALLER decides where the abort surfaces — training thread only).
        """
        checksum = blob_checksum(blob)

        def _rotate(src: str, dst: str) -> None:
            # LINK-based rotation (fall back to a copy where hardlinks
            # are unsupported): `src` — the committed latest — never
            # disappears, so at every instant of the rotate+write
            # sequence at least one slot passes its integrity check (a
            # plain rename here would open a crash window with NO
            # loadable latest at all)
            lnk = dst + ".lnk"
            try:
                if os.path.exists(lnk):
                    os.remove(lnk)
                os.link(src, lnk)
            except OSError:
                shutil.copyfile(src, lnk)
            os.replace(lnk, dst)

        def _save():
            self._io_fault()
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            if keep_prev and os.path.exists(path):
                # blob then sidecar: a crash between the two leaves
                # .prev's sidecar one generation stale, which the
                # integrity check REJECTS (fail-safe) — the still-intact
                # `path` remains the loadable anchor through that window
                _rotate(path, path + ".prev")
                if os.path.exists(path + ".sum"):
                    _rotate(path + ".sum", path + ".prev.sum")
            os.replace(tmp, path)
            write_sidecar(path, checksum, len(blob))

        if run_with_retry(_save, self.retry,
                          what=f"checkpoint save {os.path.basename(path)}"):
            self.escalator.record_success()
            return True
        self.escalator.record_failure(f"save {path}")
        emit_event(self.telemetry, "checkpoint_save_failed",
                   path=os.path.basename(path),
                   consecutive=self.escalator.consecutive)
        return False

    def _write(self, path: str, state: ServerState,
               keep_prev: bool = False) -> None:
        self._write_blob(path, _state_to_bytes(state), keep_prev=keep_prev)
        self.escalator.check()

    # -- load ----------------------------------------------------------
    def load(self, template: ServerState,
             name: str = LATEST) -> Optional[ServerState]:
        if self.backend == "orbax":
            self._commit_pending_latest()
            if name == LATEST:
                return self._orbax_load_latest(template)
            path = self._orbax_path(name)
            restored = self._orbax_load(path, template)
            if restored is None:
                # crash mid-swap: the previous version is parked at .old
                restored = self._orbax_load(path + ".old", template)
            return restored
        self._mp_wait()  # an in-flight async latest must land first
        path = os.path.join(self.model_dir, name)
        candidates = [path]
        if name == LATEST:
            # two-slot fallback: the previous generation survives at
            # .prev; a corrupted/torn latest resumes one round back
            # instead of not at all
            candidates.append(os.path.join(self.model_dir, LATEST_PREV))
        for cand in candidates:
            if not os.path.exists(cand):
                continue
            with open(cand, "rb") as fh:
                blob = fh.read()
            try:
                verify_blob(cand, blob)
                state = _state_from_bytes(blob, template)
            except (KeyboardInterrupt, SystemExit):
                raise
            except CheckpointCorruptionError as exc:
                self._recover(f"integrity check failed: {exc}", cand)
                continue
            except Exception as exc:  # torn/truncated msgpack
                self._recover(f"unreadable checkpoint: {exc!r}", cand)
                continue
            if cand != path:
                self._recover("restored from backup slot", cand)
            return state
        return None

    def _orbax_load_latest(self, template: ServerState
                           ) -> Optional[ServerState]:
        """Latest via the pointer, with checksum verification and
        automatic fallback to the OTHER slot on corruption/torn-write
        (the previous committed generation keeps living there until the
        slot is reused two saves later)."""
        parsed = self._latest_ptr()
        if parsed is None:
            return None
        slot = parsed.get("slot")
        other = (self._LATEST_SLOTS[1] if slot == self._LATEST_SLOTS[0]
                 else self._LATEST_SLOTS[0])
        for cand in (slot, other):
            path = self._orbax_path(cand)
            if not os.path.isdir(path):
                continue
            if cand == slot and parsed.get("crc32"):
                actual = tree_checksum(path)
                if actual != parsed["crc32"]:
                    self._recover(
                        f"slot checksum {actual} != recorded "
                        f"{parsed['crc32']}", path)
                    continue
            try:
                restored = self._orbax_load(path, template)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as exc:
                self._recover(f"unreadable orbax slot: {exc!r}", path)
                continue
            if restored is None:
                continue
            if cand != slot:
                self._recover("restored from backup slot", path)
            return restored
        return None

    def load_best(self, template: ServerState,
                  metric_name: str) -> Optional[ServerState]:
        return self.load(template, f"best_val_{metric_name}_model.msgpack")

    # -- status log ----------------------------------------------------
    def update_status(self, update: Dict[str, Any]) -> Dict[str, Any]:
        return update_json_log(os.path.join(self.model_dir, STATUS_LOG), update)

    def read_status(self) -> Dict[str, Any]:
        path = os.path.join(self.model_dir, STATUS_LOG)
        if os.path.exists(path):
            with open(path) as fh:
                return json.load(fh)
        return {}
