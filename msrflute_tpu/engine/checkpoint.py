"""Checkpoint / resume.

Parity target: reference §5.4 — tar checkpoints of
model/optimizer/lr-scheduler state (``core/trainer.py:753-775``),
``latest_model`` every round + ``epoch<i>`` and best-model copies every
``model_backup_freq`` (``core/server.py:530-558``), ``status_log.json``
(``core/server.py:477-490``), resume (``core/server.py:183-204``), and
fallback-to-best (``core/server.py:561-578``).

Format: flax msgpack serialization of the full :class:`ServerState` pytree
(+ a sidecar JSON with round/best-metric bookkeeping).  Saves use the
3-retry wrapper (reference ``utils/utils.py:348-359``).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
from typing import Any, Dict, Optional, Tuple

import jax
from flax import serialization

from ..utils.io import try_except_save, update_json_log
from ..utils.logging import print_rank
from .round import ServerState

LATEST = "latest_model.msgpack"
STATUS_LOG = "status_log.json"


def _payload(state: ServerState) -> dict:
    """The one checkpointed dict, shared by every backend — add new
    ServerState fields HERE (and in :func:`_merge`) only."""
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "strategy_state": state.strategy_state,
        "round": state.round,
    }


def _merge(template: ServerState, restored: dict) -> ServerState:
    """Restore typed pytrees (optax namedtuples etc.) from a plain
    state-dict by merging onto the RAW template payload."""
    merged = serialization.from_state_dict(
        _payload(template), restored)
    return ServerState(
        params=merged["params"],
        opt_state=merged["opt_state"],
        strategy_state=merged["strategy_state"],
        round=int(restored.get("round", 0)),
    )


def _state_to_bytes(state: ServerState) -> bytes:
    return serialization.msgpack_serialize(
        serialization.to_state_dict(jax.device_get(_payload(state))))


def _state_from_bytes(data: bytes, template: ServerState) -> ServerState:
    return _merge(template, serialization.msgpack_restore(data))


def load_pretrained_params(path: str, template_params,
                           data_path: Optional[str] = None):
    """Load model params from a checkpoint file for warm-starting training
    (reference ``model_config.pretrained_model_path``, ``core/config.py:93``;
    relative paths resolve against ``data_path``, ``core/config.py:744-745``).

    Accepts a full :class:`ServerState` dump from EITHER backend (msgpack
    file or orbax checkpoint directory — anything this module wrote:
    ``latest``/``epoch<i>``/``best_val_*``) or a bare params-pytree
    msgpack; only the params are taken.
    """
    if not os.path.isabs(path) and not os.path.exists(path) and data_path:
        path = os.path.join(data_path, path)
    if os.path.isdir(path):
        # orbax checkpoint directory
        import orbax.checkpoint as ocp
        with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as cp:
            restored = cp.restore(os.path.abspath(path))
    else:
        with open(path, "rb") as fh:
            restored = serialization.msgpack_restore(fh.read())
    target = jax.device_get(template_params)
    if isinstance(restored, dict) and "params" in restored:
        restored = restored["params"]
    return serialization.from_state_dict(target, restored)


class CheckpointManager:
    """latest/every-N/best checkpoint policy + status log.

    Backends: ``msgpack`` (default; one flat file, synchronous) or
    ``orbax`` (``server_config.checkpoint_backend: orbax``) — async saves
    via ``orbax.checkpoint.AsyncCheckpointer``, so serialization/IO of the
    previous round's state overlaps the next rounds' device compute (the
    TPU-framework norm for big models; the reference's torch.save has no
    async path).

    Async durability contract: a round's checkpoint becomes the committed
    resume anchor at the NEXT save/load/wait (two-slot + pointer for
    ``latest``, tmp-dir + rename for ``best``), so a hard crash can lose
    at most the one most recent round — the inherent async window.  Save
    failures warn and training continues, mirroring ``try_except_save``.
    """

    def __init__(self, model_dir: str, backup_freq: int = 100,
                 backend: str = "msgpack", async_latest: bool = False):
        self.model_dir = model_dir
        self.backup_freq = max(int(backup_freq), 1)
        if backend not in ("msgpack", "orbax"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.backend = backend
        self._orbax = None
        self._pending_slot = None
        self._pending_renames = []  # [(tmp_dir, final_dir)] after async save
        if backend == "orbax":
            import orbax.checkpoint as ocp
            self._ocp = ocp
            self._orbax = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # msgpack async-latest: per-round ``latest`` saves hand a DEVICE
        # snapshot to a writer thread, so the device->host transfer and
        # the disk write overlap the next rounds' compute (the per-round
        # sync fetch is the faithful-mode fullrun's dominant cost on a
        # remote-attached chip; SURVEY §7 explicitly budgets for async
        # checkpointing).  Same durability contract as the orbax path: a
        # hard crash can lose at most the in-flight save.
        self.async_latest = bool(async_latest) and backend == "msgpack"
        self._mp_cond = threading.Condition()
        self._mp_mailbox = None   # single-slot device snapshot (see _mp_submit)
        self._mp_busy = False
        self._mp_worker = None
        os.makedirs(model_dir, exist_ok=True)

    # -- orbax helpers -------------------------------------------------
    _LATEST_SLOTS = ("latest_model.orbax.a", "latest_model.orbax.b")
    _LATEST_PTR = "latest_model.orbax.ptr"

    def _orbax_path(self, name: str) -> str:
        # orbax checkpoints are directories; keep the msgpack names with a
        # .orbax suffix so both backends can coexist in one model_dir
        return os.path.join(os.path.abspath(self.model_dir),
                            name.replace(".msgpack", ".orbax"))

    def _orbax_save(self, path: str, state: ServerState) -> None:
        """Issue one async save (best-effort: failures warn, training goes
        on — the orbax analogue of the msgpack path's try_except_save)."""
        payload = serialization.to_state_dict(_payload(state))
        self._drain()  # one in-flight save at a time + commit renames
        try:
            self._orbax.save(path, args=self._ocp.args.StandardSave(payload),
                             force=True)
        except Exception as exc:  # disk-full/NFS blip: warn, keep training
            print_rank(f"orbax save to {path} failed: {exc!r}",
                       loglevel=logging.WARNING)

    def _drain(self) -> None:
        """Finish the in-flight save (tolerating failure) and perform any
        deferred directory renames."""
        try:
            self._orbax.wait_until_finished()
        except Exception as exc:
            print_rank(f"async checkpoint save failed: {exc!r}",
                       loglevel=logging.WARNING)
            self._pending_slot = None
            self._pending_renames.clear()
            return
        for tmp, final in self._pending_renames:
            if not os.path.isdir(tmp):
                continue
            old = final + ".old"
            try:
                # a crash between the renames below can leave a stale .old
                # behind; clear it or os.rename onto it raises ENOTEMPTY
                # forever after
                shutil.rmtree(old, ignore_errors=True)
                if os.path.isdir(final):
                    os.rename(final, old)
                os.rename(tmp, final)
                shutil.rmtree(old, ignore_errors=True)
            except OSError as exc:
                print_rank(f"checkpoint rename {tmp} -> {final} failed: "
                           f"{exc!r}", loglevel=logging.WARNING)
        self._pending_renames.clear()

    def _orbax_load(self, path: str,
                    template: ServerState) -> Optional[ServerState]:
        if not os.path.isdir(path):
            return None
        self._orbax.wait_until_finished()
        target = serialization.to_state_dict(jax.device_get(
            _payload(template)))
        restored = self._orbax.restore(
            path, args=self._ocp.args.StandardRestore(target))
        return _merge(template, restored)

    def _commit_pending_latest(self) -> None:
        """Point the latest-pointer at the slot whose async save has now
        finished (two-slot scheme: the previous committed slot stays valid
        through the entire save window, so a crash mid-save never loses
        the resume anchor — the async analogue of tmp+os.replace)."""
        if self._pending_slot is None:
            self._drain()
            return
        slot = self._pending_slot
        self._pending_slot = None
        self._drain()
        if not os.path.isdir(self._orbax_path(slot)):
            return  # the save failed; keep pointing at the old slot
        ptr = os.path.join(self.model_dir, self._LATEST_PTR)
        tmp = ptr + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(slot)
        os.replace(tmp, ptr)

    def _latest_slot(self) -> Optional[str]:
        ptr = os.path.join(self.model_dir, self._LATEST_PTR)
        if not os.path.exists(ptr):
            return None
        with open(ptr) as fh:
            return fh.read().strip()

    def wait(self) -> None:
        """Block until pending async saves are durable (call before reading
        checkpoint files externally or at process exit)."""
        if self._orbax is not None:
            self._commit_pending_latest()
        self._mp_wait()

    # -- msgpack async-latest writer ------------------------------------
    def _mp_wait(self) -> None:
        if self._mp_worker is None:
            return
        with self._mp_cond:
            while self._mp_mailbox is not None or self._mp_busy:
                self._mp_cond.wait()

    def _mp_loop(self) -> None:
        path = os.path.join(self.model_dir, LATEST)
        while True:
            with self._mp_cond:
                while self._mp_mailbox is None:
                    self._mp_cond.wait()
                snap = self._mp_mailbox
                self._mp_mailbox = None
                self._mp_busy = True
            try:
                blob = serialization.msgpack_serialize(
                    serialization.to_state_dict(jax.device_get(snap)))
                del snap  # release the HBM snapshot before the disk write
                self._write_blob(path, blob)
                del blob
            except Exception as exc:  # never kill training from the writer
                print_rank(f"async latest save failed: {exc!r}",
                           loglevel=logging.WARNING)
            finally:
                with self._mp_cond:
                    self._mp_busy = False
                    self._mp_cond.notify_all()

    def _mp_submit(self, state: ServerState) -> None:
        # single-slot, not latest-wins: wait for the in-flight save first,
        # so the on-disk latest can lag the status log by AT MOST the one
        # in-flight round — the same durability window the orbax path
        # documents.  (Latest-wins would let a slow disk stack unbounded
        # skew between latest_model and status_log.json, and resume pairs
        # the two.)  The wait also bounds snapshot HBM to one extra copy.
        if self._mp_worker is None:
            self._mp_worker = threading.Thread(
                target=self._mp_loop, name="ckpt-latest-writer", daemon=True)
            self._mp_worker.start()
        with self._mp_cond:
            while self._mp_mailbox is not None or self._mp_busy:
                self._mp_cond.wait()
        # device-side copy: the round step donates the live param/opt
        # buffers, so the snapshot must be arrays nothing else consumes.
        # The copies are enqueued on the device stream BEFORE any later
        # donating program, so they read the pre-donation values; the
        # writer thread's device_get then overlaps the next rounds.
        # Host numpy leaves (e.g. mutable strategy_state arrays) are
        # np.copy'd for the same reason: a by-reference share would let
        # an in-place mutation on the training thread reach the writer's
        # serialize mid-flight and persist a torn value.
        import jax.numpy as jnp
        import numpy as _np
        snap = jax.tree.map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array)
            else (_np.copy(x) if isinstance(x, _np.ndarray) else x),
            _payload(state))
        with self._mp_cond:
            self._mp_mailbox = snap
            self._mp_cond.notify()

    # -- save ----------------------------------------------------------
    def save_latest(self, state: ServerState) -> None:
        if self.backend == "orbax":
            self._commit_pending_latest()
            committed = self._latest_slot()
            slot = (self._LATEST_SLOTS[1]
                    if committed == self._LATEST_SLOTS[0]
                    else self._LATEST_SLOTS[0])
            self._orbax_save(self._orbax_path(slot), state)
            self._pending_slot = slot
            return
        if self.async_latest:
            self._mp_submit(state)
            return
        self._write(os.path.join(self.model_dir, LATEST), state)

    def backup(self, state: ServerState, round_no: int,
               best_names: Tuple[str, ...] = ()) -> None:
        """Every ``backup_freq`` rounds: ``epoch<i>`` copy + snapshots of the
        best-model files (reference ``core/server.py:530-558``)."""
        if round_no % self.backup_freq:
            return
        if self.backend == "orbax":
            self.wait()  # copies must see complete checkpoints
            slot = self._latest_slot()
            src = self._orbax_path(slot) if slot else ""
            if src and os.path.isdir(src):
                dst = self._orbax_path(f"epoch{round_no}.orbax")
                if not os.path.isdir(dst):
                    shutil.copytree(src, dst)
            for name in best_names:
                best = self._orbax_path(f"best_val_{name}_model.orbax")
                dst = self._orbax_path(
                    f"best_val_{name}_model_epoch{round_no}.orbax")
                if os.path.isdir(best) and not os.path.isdir(dst):
                    shutil.copytree(best, dst)
            return
        self._mp_wait()  # the epoch copy must see the newest latest file
        src = os.path.join(self.model_dir, LATEST)
        if os.path.exists(src):
            shutil.copyfile(src, os.path.join(self.model_dir,
                                              f"epoch{round_no}.msgpack"))
        for name in best_names:
            best = os.path.join(self.model_dir, f"best_val_{name}_model.msgpack")
            if os.path.exists(best):
                shutil.copyfile(best, os.path.join(
                    self.model_dir, f"best_val_{name}_model_epoch{round_no}.msgpack"))

    def save_best(self, state: ServerState, metric_name: str) -> None:
        """Best-val checkpoint on improvement (reference
        ``core/evaluation.py:103-109``)."""
        if self.backend == "orbax":
            # async save to a .new dir; the rename into place happens at
            # the next drain, with the previous best parked at .old until
            # the swap completes — no moment without a readable best
            final = self._orbax_path(f"best_val_{metric_name}_model.orbax")
            tmp = final + ".new"
            shutil.rmtree(tmp, ignore_errors=True)
            self._orbax_save(tmp, state)
            self._pending_renames.append((tmp, final))
            return
        self._write(os.path.join(
            self.model_dir, f"best_val_{metric_name}_model.msgpack"), state)

    @staticmethod
    def _write_blob(path: str, blob: bytes) -> None:
        """Atomic tmp-write + rename, with the retry policy — THE write
        recipe, shared by the sync and async-latest paths."""
        def _save():
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        try_except_save(_save)

    def _write(self, path: str, state: ServerState) -> None:
        self._write_blob(path, _state_to_bytes(state))

    # -- load ----------------------------------------------------------
    def load(self, template: ServerState,
             name: str = LATEST) -> Optional[ServerState]:
        if self.backend == "orbax":
            self._commit_pending_latest()
            if name == LATEST:
                slot = self._latest_slot()
                if slot is None:
                    return None
                return self._orbax_load(self._orbax_path(slot), template)
            path = self._orbax_path(name)
            restored = self._orbax_load(path, template)
            if restored is None:
                # crash mid-swap: the previous version is parked at .old
                restored = self._orbax_load(path + ".old", template)
            return restored
        self._mp_wait()  # an in-flight async latest must land first
        path = os.path.join(self.model_dir, name)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as fh:
            return _state_from_bytes(fh.read(), template)

    def load_best(self, template: ServerState,
                  metric_name: str) -> Optional[ServerState]:
        return self.load(template, f"best_val_{metric_name}_model.msgpack")

    # -- status log ----------------------------------------------------
    def update_status(self, update: Dict[str, Any]) -> Dict[str, Any]:
        return update_json_log(os.path.join(self.model_dir, STATUS_LOG), update)

    def read_status(self) -> Dict[str, Any]:
        path = os.path.join(self.model_dir, STATUS_LOG)
        if os.path.exists(path):
            with open(path) as fh:
                return json.load(fh)
        return {}
