"""Checkpoint / resume.

Parity target: reference §5.4 — tar checkpoints of
model/optimizer/lr-scheduler state (``core/trainer.py:753-775``),
``latest_model`` every round + ``epoch<i>`` and best-model copies every
``model_backup_freq`` (``core/server.py:530-558``), ``status_log.json``
(``core/server.py:477-490``), resume (``core/server.py:183-204``), and
fallback-to-best (``core/server.py:561-578``).

Format: flax msgpack serialization of the full :class:`ServerState` pytree
(+ a sidecar JSON with round/best-metric bookkeeping).  Saves use the
3-retry wrapper (reference ``utils/utils.py:348-359``).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
from flax import serialization

from ..utils.io import try_except_save, update_json_log
from .round import ServerState

LATEST = "latest_model.msgpack"
STATUS_LOG = "status_log.json"


def _state_to_bytes(state: ServerState) -> bytes:
    payload = {
        "params": state.params,
        "opt_state": state.opt_state,
        "strategy_state": state.strategy_state,
        "round": state.round,
    }
    return serialization.msgpack_serialize(
        serialization.to_state_dict(jax.device_get(payload)))


def _state_from_bytes(data: bytes, template: ServerState) -> ServerState:
    target = {
        "params": jax.device_get(template.params),
        "opt_state": jax.device_get(template.opt_state),
        "strategy_state": jax.device_get(template.strategy_state),
        "round": template.round,
    }
    restored = serialization.msgpack_restore(data)
    merged = serialization.from_state_dict(target, restored)
    return ServerState(
        params=merged["params"],
        opt_state=merged["opt_state"],
        strategy_state=merged["strategy_state"],
        round=int(restored.get("round", 0)),
    )


def load_pretrained_params(path: str, template_params,
                           data_path: Optional[str] = None):
    """Load model params from a checkpoint file for warm-starting training
    (reference ``model_config.pretrained_model_path``, ``core/config.py:93``;
    relative paths resolve against ``data_path``, ``core/config.py:744-745``).

    Accepts either a full :class:`ServerState` dump (any file this module
    wrote — ``latest``/``epoch<i>``/``best_val_*``) or a bare params-pytree
    msgpack; only the params are taken.
    """
    if not os.path.isabs(path) and not os.path.exists(path) and data_path:
        path = os.path.join(data_path, path)
    with open(path, "rb") as fh:
        restored = serialization.msgpack_restore(fh.read())
    target = jax.device_get(template_params)
    if isinstance(restored, dict) and "params" in restored:
        restored = restored["params"]
    return serialization.from_state_dict(target, restored)


class CheckpointManager:
    """latest/every-N/best checkpoint policy + status log."""

    def __init__(self, model_dir: str, backup_freq: int = 100):
        self.model_dir = model_dir
        self.backup_freq = max(int(backup_freq), 1)
        os.makedirs(model_dir, exist_ok=True)

    # -- save ----------------------------------------------------------
    def save_latest(self, state: ServerState) -> None:
        self._write(os.path.join(self.model_dir, LATEST), state)

    def backup(self, state: ServerState, round_no: int,
               best_names: Tuple[str, ...] = ()) -> None:
        """Every ``backup_freq`` rounds: ``epoch<i>`` copy + snapshots of the
        best-model files (reference ``core/server.py:530-558``)."""
        if round_no % self.backup_freq:
            return
        src = os.path.join(self.model_dir, LATEST)
        if os.path.exists(src):
            shutil.copyfile(src, os.path.join(self.model_dir,
                                              f"epoch{round_no}.msgpack"))
        for name in best_names:
            best = os.path.join(self.model_dir, f"best_val_{name}_model.msgpack")
            if os.path.exists(best):
                shutil.copyfile(best, os.path.join(
                    self.model_dir, f"best_val_{name}_model_epoch{round_no}.msgpack"))

    def save_best(self, state: ServerState, metric_name: str) -> None:
        """Best-val checkpoint on improvement (reference
        ``core/evaluation.py:103-109``)."""
        self._write(os.path.join(
            self.model_dir, f"best_val_{metric_name}_model.msgpack"), state)

    def _write(self, path: str, state: ServerState) -> None:
        blob = _state_to_bytes(state)
        def _save():
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        try_except_save(_save)

    # -- load ----------------------------------------------------------
    def load(self, template: ServerState,
             name: str = LATEST) -> Optional[ServerState]:
        path = os.path.join(self.model_dir, name)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as fh:
            return _state_from_bytes(fh.read(), template)

    def load_best(self, template: ServerState,
                  metric_name: str) -> Optional[ServerState]:
        return self.load(template, f"best_val_{metric_name}_model.msgpack")

    # -- status log ----------------------------------------------------
    def update_status(self, update: Dict[str, Any]) -> Dict[str, Any]:
        return update_json_log(os.path.join(self.model_dir, STATUS_LOG), update)

    def read_status(self) -> Dict[str, Any]:
        path = os.path.join(self.model_dir, STATUS_LOG)
        if os.path.exists(path):
            with open(path) as fh:
                return json.load(fh)
        return {}
