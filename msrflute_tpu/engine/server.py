"""Server round loop — the host-side controller.

Parity target: reference ``OptimizationServer`` (``core/server.py:48-578``).
Everything data-dependent stays here (sampling, eval cadence, LR plateau
decay, checkpointing, logging, timing); everything numeric is inside the
jitted :class:`~msrflute_tpu.engine.round.RoundEngine` program.  Feature map:

- per-round client sampling, incl. ``"lo:hi"`` random count
  (``core/server.py:284-302``)                          -> :meth:`_sample`
- model "broadcast"/collection                          -> RoundEngine
- per-client stats + strategy processing
  (``core/server.py:337-427``)                          -> RoundEngine
- periodic val/test + best tracking (``:448-462``)      -> :meth:`_maybe_eval`
- client-LR decay on val plateau (``:464-469``)         -> ``lr_weight``
- checkpoint/backup/fallback (``:471-475,530-578``)     -> CheckpointManager
- status log (``:477-490``)                             -> ``status_log.json``
- timing stats (``:492-521``)                           -> ``run_stats``
- initial val/test before training (``:236``)           -> ``initial_val``
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..config import FLUTEConfig, parse_clients_per_round
from ..data.batching import pack_eval_batches, pack_round_batches, steps_for
from ..data.dataset import BaseDataset
from ..models.base import BaseTask
from ..optim import PlateauTracker, make_lr_schedule
from ..parallel.mesh import CLIENTS_AXIS, make_mesh, pad_to_mesh
from ..strategies import select_strategy
from ..utils.logging import log_metric, print_rank
from ..utils.metrics import Metric, MetricsDict
from .checkpoint import CheckpointManager
from .evaluation import build_eval_fn, evaluate
from .round import RoundEngine, ServerState


class OptimizationServer:
    """Single-controller federated optimization loop."""

    def __init__(self, task: BaseTask, config: FLUTEConfig,
                 train_dataset: BaseDataset,
                 val_dataset: Optional[BaseDataset] = None,
                 test_dataset: Optional[BaseDataset] = None,
                 model_dir: str = "./models", mesh=None,
                 seed: int = 0):
        self.task = task
        self.config = config
        self.train_dataset = train_dataset
        self.val_dataset = val_dataset
        self.test_dataset = test_dataset
        self.mesh = mesh if mesh is not None else make_mesh()

        sc = config.server_config
        dp = config.dp_config
        strategy_cls = select_strategy(config.strategy)
        self.strategy = strategy_cls(config, dp)
        self.engine = RoundEngine(task, config, self.strategy, self.mesh)
        self.ckpt = CheckpointManager(model_dir,
                                      backup_freq=sc.get("model_backup_freq", 100))

        # LR machinery: server-side schedule + client plateau decay
        self.initial_lr_client = float(sc.get("initial_lr_client", 0.01))
        self.lr_decay_factor = float(sc.get("lr_decay_factor", 1.0))
        self.lr_weight = 1.0
        self.server_lr_schedule = make_lr_schedule(
            sc.annealing_config, float(sc.optimizer_config.get("lr", 1.0)))
        self.plateau: Optional[PlateauTracker] = None
        if sc.annealing_config is not None and \
                sc.annealing_config.get("type") == "val_loss":
            self.plateau = PlateauTracker(
                sc.annealing_config, float(sc.optimizer_config.get("lr", 1.0)))

        self.best_model_criterion = sc.get("best_model_criterion", "loss")
        self.fall_back_to_best = bool(sc.get("fall_back_to_best_model", False))
        self.best_val: Dict[str, Metric] = {}

        # static round-program geometry
        cc = config.client_config
        self.batch_size = int(cc.data_config.train.get("batch_size", 32))
        self.desired_max_samples = cc.get("desired_max_samples") or \
            cc.data_config.train.get("desired_max_samples")
        max_client_samples = int(max(train_dataset.num_samples))
        self.max_steps = steps_for(max_client_samples, self.batch_size,
                                   self.desired_max_samples)

        self._eval_fn = build_eval_fn(task, self.mesh)
        self._np_rng = np.random.default_rng(seed)
        self._rng = jax.random.PRNGKey(seed)
        self.run_stats: Dict[str, list] = {
            "secsPerRound": [], "secsPerRoundHousekeeping": []}

        self.state = self.engine.init_state(self._rng)
        if sc.get("resume_from_checkpoint", False):
            restored = self.ckpt.load(self.state)
            if restored is not None:
                self.state = restored
                status = self.ckpt.read_status()
                self.lr_weight = float(status.get("weight", 1.0))
                print_rank(f"resumed from checkpoint at round {self.state.round}")

    # ------------------------------------------------------------------
    def _sample(self) -> list:
        sc = self.config.server_config
        n = parse_clients_per_round(sc.get("num_clients_per_iteration", 10),
                                    self._np_rng)
        n = min(n, len(self.train_dataset))
        # random.sample equivalent (core/server.py:300-302)
        return list(self._np_rng.choice(len(self.train_dataset), size=n,
                                        replace=False))

    # ------------------------------------------------------------------
    def run(self) -> ServerState:
        return self.train()

    def train(self) -> ServerState:
        sc = self.config.server_config
        max_iteration = int(sc.get("max_iteration", 100))
        val_freq = int(sc.get("val_freq", 20) or 20)
        rec_freq = int(sc.get("rec_freq", 20) or 20)

        if self.state.round == 0 and sc.get("initial_val", True):
            self._maybe_eval("val", self.state.round, force=True)
        if self.state.round == 0 and sc.get("initial_rec", False):
            self._maybe_eval("test", self.state.round, force=True)

        ndev = self.mesh.shape[CLIENTS_AXIS]
        for round_no in range(self.state.round, max_iteration):
            tic = time.time()
            client_lr = self.initial_lr_client * self.lr_weight
            server_lr = (self.plateau.lr if self.plateau is not None
                         else self.server_lr_schedule(round_no))

            sampled = self._sample()
            batch = pack_round_batches(
                self.train_dataset, sampled, self.batch_size, self.max_steps,
                rng=self._np_rng, pad_clients_to=pad_to_mesh(len(sampled), self.mesh),
                desired_max_samples=self.desired_max_samples)

            self._rng, round_rng = jax.random.split(self._rng)
            self.state, stats = self.engine.run_round(
                self.state, batch, client_lr, server_lr, round_rng)

            toc = time.time()
            self.run_stats["secsPerRound"].append(toc - tic)

            # round logging (reference core/server.py:362-395 + AzureML)
            stats = {k: float(v) for k, v in jax.device_get(stats).items()}
            n_clients = max(stats["client_count"], 1.0)
            log_metric("Training loss",
                       stats["train_loss_sum"] / n_clients, step=round_no)
            log_metric("LR for agg. opt.", server_lr, step=round_no)
            log_metric("Client learning rate", client_lr, step=round_no)
            log_metric("Agg. grad norm", stats["agg_grad_norm"], step=round_no)

            housekeeping_tic = time.time()
            improved = False
            if (round_no + 1) % val_freq == 0:
                improved = self._maybe_eval("val", round_no + 1)
                # client-LR decay on val plateau (core/server.py:464-469)
                if not improved and self.lr_decay_factor != 1.0:
                    self.lr_weight *= float(self.lr_decay_factor)
                    print_rank(f"decayed client lr weight to {self.lr_weight}")
                if self.plateau is not None and "loss" in self._last_val:
                    self.plateau.step(self._last_val["loss"].value)
                if self.fall_back_to_best and not improved:
                    self._fall_back()
            if (round_no + 1) % rec_freq == 0 and self.test_dataset is not None:
                self._maybe_eval("test", round_no + 1)

            self.ckpt.save_latest(self.state)
            self.ckpt.backup(self.state, round_no + 1,
                             best_names=tuple(self.best_val))
            self.ckpt.update_status({
                "i": round_no + 1,
                "weight": self.lr_weight,
                **{f"best_val_{k}": m.value for k, m in self.best_val.items()},
            })
            self.run_stats["secsPerRoundHousekeeping"].append(
                time.time() - housekeeping_tic)
        self._log_timing()
        return self.state

    # ------------------------------------------------------------------
    _last_val: MetricsDict = {}

    def _maybe_eval(self, split: str, round_no: int, force: bool = False) -> bool:
        dataset = self.val_dataset if split == "val" else self.test_dataset
        if dataset is None or len(dataset) == 0:
            return False
        ndev = self.mesh.shape[CLIENTS_AXIS]
        batch_cfg = (self.config.server_config.data_config.val if split == "val"
                     else self.config.server_config.data_config.test)
        bs = int(batch_cfg.get("batch_size", self.batch_size))
        batches = pack_eval_batches(dataset, bs, pad_steps_to_multiple_of=ndev)
        metrics = evaluate(self.task, self._eval_fn, self.state.params,
                           batches, self.mesh)
        for name, metric in metrics.items():
            log_metric(f"{split.capitalize()} {name}", metric.value, step=round_no)

        improved = False
        if split == "val":
            self._last_val = metrics
            for name, metric in metrics.items():
                prev = self.best_val.get(name)
                if prev is None or metric.is_better_than(prev):
                    self.best_val[name] = metric
                    self.ckpt.save_best(self.state, name)
                    if name == self.best_model_criterion:
                        improved = True
        return improved

    def _fall_back(self) -> None:
        """Reload the best checkpoint, preserving current LR weight
        (reference ``core/server.py:561-578``)."""
        restored = self.ckpt.load_best(self.state, self.best_model_criterion)
        if restored is not None:
            self.state = ServerState(restored.params, restored.opt_state,
                                     restored.strategy_state, self.state.round)
            print_rank("fell back to previous best model")

    def _log_timing(self) -> None:
        for key, values in self.run_stats.items():
            if values:
                log_metric(f"{key} (mean)", float(np.mean(values)))


def select_server(server_type: str):
    """Reference ``select_server`` (``core/server.py:581-597``):
    ``personalization`` -> PersonalizationServer, else OptimizationServer."""
    if (server_type or "").lower() == "personalization":
        from .personalization import PersonalizationServer
        return PersonalizationServer
    return OptimizationServer
