"""Server round loop — the host-side controller.

Parity target: reference ``OptimizationServer`` (``core/server.py:48-578``).
Everything data-dependent stays here (sampling, eval cadence, LR plateau
decay, checkpointing, logging, timing); everything numeric is inside the
jitted :class:`~msrflute_tpu.engine.round.RoundEngine` program.  Feature map:

- per-round client sampling, incl. ``"lo:hi"`` random count
  (``core/server.py:284-302``)                          -> :meth:`_sample`
- model "broadcast"/collection                          -> RoundEngine
- per-client stats + strategy processing
  (``core/server.py:337-427``)                          -> RoundEngine
- periodic val/test + best tracking (``:448-462``)      -> :meth:`_maybe_eval`
- client-LR decay on val plateau (``:464-469``)         -> ``lr_weight``
- checkpoint/backup/fallback (``:471-475,530-578``)     -> CheckpointManager
- status log (``:477-490``)                             -> ``status_log.json``
- timing stats (``:492-521``)                           -> ``run_stats``
- initial val/test before training (``:236``)           -> ``initial_val``
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import FLUTEConfig, parse_clients_per_round
from ..data.batching import pack_eval_batches, pack_round_batches, steps_for
from ..data.dataset import BaseDataset
from ..models.base import BaseTask
from ..optim import PlateauTracker, make_lr_schedule
from ..parallel.mesh import CLIENTS_AXIS, make_mesh, pad_to_mesh
from ..resilience import PreemptionHandler, make_chaos
from ..traffic import STALE_HIST_BINS, make_traffic
from ..resilience.integrity import DurableIOLadder, RetryPolicy
from ..strategies import select_strategy
from ..telemetry import NULL_SPAN, emit_event, make_telemetry
from ..telemetry.rollup import host_rss_bytes
from ..utils.logging import flush_metrics, log_metric, print_rank
from ..utils.metrics import Metric, MetricsDict
from ..utils.strict import strict_transfer_scope
from .checkpoint import CheckpointManager
from .evaluation import build_eval_fn, evaluate
from .round import RoundEngine, ServerState


class OptimizationServer:
    """Single-controller federated optimization loop."""

    def __init__(self, task: BaseTask, config: FLUTEConfig,
                 train_dataset: BaseDataset,
                 val_dataset: Optional[BaseDataset] = None,
                 test_dataset: Optional[BaseDataset] = None,
                 server_train_dataset: Optional[BaseDataset] = None,
                 model_dir: str = "./models", mesh=None,
                 seed: int = 0):
        self.task = task
        self.config = config
        self.train_dataset = train_dataset
        self.val_dataset = val_dataset
        self.test_dataset = test_dataset
        self.mesh = mesh if mesh is not None else make_mesh()

        sc = config.server_config
        dp = config.dp_config
        #: universal overlap (PR 6): device-resident strategy carry state
        #: — consulted by strategy selection, the host-orchestrated
        #: predicate, and the RL construction below
        self._fused_carry = bool(sc.get("fused_carry", False))
        strategy_cls = self._select_strategy(config)
        if sc.get("robust"):
            # fluteshield (server_config.robust): a stack aggregator
            # (trimmed_mean / median) swaps in the stack-combining
            # RobustFedAvg; screening-only configs keep the plain
            # strategy.  Non-FedAvg strategies are refused loudly — a
            # robust block that silently aggregated unscreened payloads
            # is the quiet failure this layer exists to prevent.
            from ..strategies.robust import select_robust_strategy
            self.strategy = select_robust_strategy(config, dp, strategy_cls)
        else:
            self.strategy = strategy_cls(config, dp)
        # universal overlap (server_config.fused_carry): strategies whose
        # cross-round state moved into device-resident carry tables
        # (SCAFFOLD controls, EF residuals, personalization heads/alphas)
        # size those tables to the client pool; a no-op for strategies
        # without carry state
        fused_carry = self._fused_carry
        if fused_carry:
            self.strategy.carry_clients = len(train_dataset)
        # fleet mode (server_config.fleet): population size becomes a
        # free variable — O(cohort) cohort draws and, for device-carry
        # strategies, a fixed-capacity page pool replacing the
        # [N, n_params] resident carry tables (engine/paging.py).
        # Parsed BEFORE the engine builds its programs: carry_rows must
        # be set before init_state sizes the tables, and the engine
        # compiles the slot operand in when paging is on.
        _fl = sc.get("fleet") or {}
        self._fleet_cfg = _fl if (_fl and _fl.get("enable", True)) else None
        self._fleet_paged = bool(
            self._fleet_cfg is not None and
            getattr(self.strategy, "device_carry", False))
        if self._fleet_cfg is not None:
            if sc.get("scaffold_device_controls") or \
                    sc.get("ef_device_residuals"):
                raise ValueError(
                    "server_config.fleet does not compose with "
                    "scaffold_device_controls / ef_device_residuals — "
                    "those keep a FULL [N, n_params] table in HBM, the "
                    "exact residency fleet paging exists to replace; "
                    "use fused_carry + fleet instead")
        if self._fleet_paged:
            from ..config import cohort_upper_bound
            cohort_hi = min(cohort_upper_bound(
                sc.get("num_clients_per_iteration", 10)),
                len(train_dataset))
            pad = pad_to_mesh(cohort_hi, self.mesh)
            depth = max(int(sc.get("pipeline_depth", 1) or 0), 0)
            rps = max(int(sc.get("rounds_per_step", 1) or 1), 1)
            # default pool: (in-flight chunks + the one being prepared)
            # cohorts' worth of rows with 2x headroom for cross-round
            # revisits, pow2-quantized; never more rows than clients
            from ..data.batching import pow2_ceil
            mesh_shards = self.mesh.shape[CLIENTS_AXIS]
            if rps > 1 and mesh_shards > 1:
                # a client resampled in a LATER round of one fused
                # chunk can land on a different shard; its carry row
                # would have to cross shards mid-program — exactly the
                # collective the sharded pool exists to avoid.  Single-
                # round chunks migrate between dispatches instead (the
                # pager force-completes the in-flight writeback), so
                # pipeline_depth still provides the overlap.
                raise ValueError(
                    "fleet paged carry on a multi-device clients mesh "
                    f"({mesh_shards} shards) requires rounds_per_step: "
                    f"1 (got {rps}) — a mid-chunk resample onto another "
                    "shard would need a cross-shard carry collective; "
                    "use pipeline_depth for overlap instead")
            auto = pow2_ceil(max(pad * rps * (depth + 1) * 2, pad + 1))
            slots = int(self._fleet_cfg.get("page_pool_slots") or auto)
            slots = min(max(slots, pad), len(train_dataset))
            # mesh-sharded pool: the slot axis splits over CLIENTS_AXIS
            # into contiguous per-shard blocks (per-device HBM =
            # slots / mesh_size rows), so the pool must be a mesh
            # multiple — quantize UP (a pool slightly past N just means
            # some slots never allocate).  The same helper re-derives
            # the geometry at mesh-elastic resume, so construction and
            # resume can never disagree on the quantization rule.
            from ..parallel.sharding import quantize_pool_slots
            slots = quantize_pool_slots(slots, self.mesh)
            # in-flight floor: with depth-N pipelining, (depth+1) chunks
            # of rps cohorts each can pin rows simultaneously — a pool
            # below that would deadlock allocation mid-run; refuse at
            # construction instead (capped at N: once every client is
            # resident no allocation ever happens)
            required = min(pad * rps * (depth + 1), len(train_dataset))
            if slots < required:
                raise ValueError(
                    f"server_config.fleet.page_pool_slots={slots} is "
                    f"below the in-flight floor {required} "
                    f"(= padded cohort {pad} x rounds_per_step {rps} x "
                    f"(pipeline_depth {depth} + 1), capped at the "
                    "population) — raise page_pool_slots or lower "
                    "pipeline_depth")
            self.strategy.carry_rows = slots
        self.engine = RoundEngine(task, config, self.strategy, self.mesh)
        #: fluteshield screening policy (None = firewall path); the ONE
        #: live Shield belongs to the engine — the server reads its
        #: counters/describe() for telemetry + the bench contract
        self.shield = self.engine.shield
        # Host-orchestrated round paths (RL, SCAFFOLD/EF host rounds,
        # personalization's overridden sampling) build their payloads
        # outside the fused round program — the ONE predicate both the
        # fluteshield and the chaos guards below key off.  fused_carry
        # lifts these strategy by strategy: a carry-mode SCAFFOLD/EF run
        # clears its host_rounds/ef_rounds flag at construction, fused RL
        # rides the round program (rl/fused.py), and a server subclass
        # whose ``_sample`` hook degrades to the base sampler under
        # fused_carry declares it with ``fused_carry_sample``
        # (personalization).
        self._sample_hooked = (
            type(self)._sample is not OptimizationServer._sample and
            not (fused_carry and
                 getattr(type(self), "fused_carry_sample", False)))
        host_orchestrated = (
            (sc.get("wantRL", False) and not fused_carry) or
            getattr(self.strategy, "host_rounds", False) or
            getattr(self.strategy, "ef_rounds", False) or
            self._sample_hooked)
        if self.shield is not None:
            if host_orchestrated:
                raise ValueError(
                    "server_config.robust requires the fused round path "
                    "— wantRL, strategy: scaffold / ef_quant, and "
                    "personalization orchestrate rounds host-side and "
                    "would aggregate unscreened payloads; drop the "
                    "robust block for this configuration")

        # ---- resilience: chaos schedule + graceful preemption --------
        # server_config.chaos (resilience/chaos.py): seeded deterministic
        # fault injection.  Client faults (dropout/straggling) ride the
        # fused round program as data operands, so they need the fused
        # path — the host-orchestrated rounds (RL, SCAFFOLD, EF) and
        # personalization's model-dependent sampling build their payloads
        # elsewhere and would silently ignore them.
        self.chaos = make_chaos(sc)
        if self.chaos is not None and (self.chaos.has_client_faults or
                                       self.chaos.has_corruption):
            if host_orchestrated:
                raise ValueError(
                    "server_config.chaos dropout_rate/straggler_rate/"
                    "corrupt_* rates require the fused round path — "
                    "wantRL, strategy: scaffold / ef_quant, and "
                    "personalization orchestrate rounds host-side and "
                    "would ignore the injected faults; zero those rates "
                    "(IO faults and preempt_at_round still apply) or "
                    "drop the feature")
        if self.chaos is not None and self.chaos.has_infra_faults and \
                not self._fleet_paged:
            raise ValueError(
                "server_config.chaos.infra requires fleet paged carry — "
                "the infra fault streams target the fleet host services "
                "(row-store spill/read, the fleet-prefetch daemon, the "
                "writeback fetch, the round marker), which only exist "
                "under server_config.fleet with a fused_carry "
                "device-carry strategy (scaffold / ef_quant / "
                "personalized); zero the infra rates or enable fleet "
                "paging")

        # ---- fluteflow: event-driven arrival plane -------------------
        # server_config.traffic (traffic/): clients become available per
        # a seeded trace and aggregation FIRES when the buffer fills —
        # the schedule replaces boundary sampling (the base _sample
        # consults it), so every plane that assumes "cohort drawn at the
        # round boundary" must either compose or refuse loudly here.
        self.traffic = make_traffic(sc, len(train_dataset))
        #: next fire the base _sample will serve; re-anchored to the
        #: resumed round at train() entry (the timeline is a pure
        #: function of the seed, so fast_forward is a cache warm-up)
        self._traffic_round = 0
        if self.traffic is not None:
            if host_orchestrated:
                raise ValueError(
                    "server_config.traffic requires the fused round "
                    "path — wantRL, strategy: scaffold / ef_quant, and "
                    "personalization orchestrate rounds host-side and "
                    "would keep boundary sampling, silently ignoring "
                    "the arrival plane; drop the traffic block for "
                    "this configuration")
            ncpi = sc.get("num_clients_per_iteration", 10)
            if not isinstance(ncpi, int) or \
                    self.traffic.buffer_size != int(ncpi):
                raise ValueError(
                    f"server_config.traffic.buffer_size "
                    f"({self.traffic.buffer_size}) must equal a FIXED "
                    f"num_clients_per_iteration (got {ncpi!r}) — the "
                    "fused program's [K, S, B] grid is compiled for "
                    "exactly K client slots, so the buffer IS the "
                    "cohort (the FedBuff buffer == K mapping)")
            if (self._fleet_cfg is not None and
                    str(self._fleet_cfg.get("sampling", "uniform"))
                    != "uniform"):
                raise ValueError(
                    "server_config.traffic and fleet.sampling != "
                    "'uniform' are two cohort-selection planes — the "
                    "arrival schedule decides WHO trains, so a "
                    "weighted/floyd fleet draw would be silently "
                    "ignored; use fleet.sampling: uniform or drop the "
                    "traffic block")
            _sa = sc.get("secure_agg") or {}
            if _sa and _sa.get("enable", True):
                _min_surv = int(_sa.get("min_survivors", 0) or 0)
                if _min_surv > self.traffic.buffer_size:
                    raise ValueError(
                        f"secure_agg.min_survivors ({_min_surv}) "
                        f"exceeds traffic.buffer_size "
                        f"({self.traffic.buffer_size}) — a buffered "
                        "fire delivers exactly buffer_size clients, so "
                        "every round would abort below the liveness "
                        "floor; lower min_survivors or raise "
                        "buffer_size")
            if self.engine.traffic_staleness:
                _mgb_t = sc.get("megabatch") or {}
                if _mgb_t and _mgb_t.get("enable", True):
                    raise ValueError(
                        "server_config.megabatch cannot compose with "
                        "traced staleness (traffic.mode: buffered + a "
                        "staleness-aware strategy): megabatch_passes "
                        "replays the strategy's in-jit staleness draw "
                        "per lane and would diverge from the trace's "
                        "true per-client staleness; drop megabatch or "
                        "run traffic.mode: sync")
        #: convergence-tier gate surface (traffic.target_accuracy): the
        #: first round whose val accuracy reaches the configured target
        #: — None until reached, and stays None when no target is set or
        #: the run never gets there.  bench.py records it per protocol
        #: and per traffic_ab arm; `scope trend` gates it alongside
        #: secs_per_round.
        self.rounds_to_target_accuracy: Optional[int] = None
        _tgt = (sc.get("traffic") or {}).get("target_accuracy")
        self.target_accuracy = (float(_tgt) if _tgt is not None else None)
        #: SIGTERM/SIGINT -> drain in-flight round -> emergency
        #: checkpoint -> resumable exit (resilience/preemption.py); the
        #: loop polls `requested` at chunk boundaries
        self.preemption = PreemptionHandler()
        self.preempted = False

        # ---- overlapped host/device round pipeline -------------------
        # pipeline_depth (schema knob, default 1): with depth >= 1 the
        # host drains round k's tail (stats decode, metric logging,
        # privacy processing, checkpoint submit) AFTER dispatching round
        # k+1, so the TPU never idles behind host bookkeeping.  Depth N
        # keeps a ring of up to N dispatched-but-undrained chunks in
        # flight (schema-validated against MAX_PIPELINE_DEPTH — the old
        # silent min(depth, 1) clamp is gone).  Depth 0 restores the
        # serial loop.  Paths that feed host results back into the NEXT
        # dispatch (host-orchestrated RL/SCAFFOLD/EF — i.e. without
        # fused_carry — server replay, the adaptive leakage threshold,
        # a live ``_sample`` hook) force serial — computed here, up
        # front, because the checkpoint-async default below depends on
        # it.
        self.pipeline_depth = max(int(sc.get("pipeline_depth", 1) or 0), 0)
        pm_cfg = config.privacy_metrics_config
        wants_adaptive = bool(
            pm_cfg is not None and pm_cfg.get("apply_metrics", False)
            and pm_cfg.get("adaptive_leakage_threshold"))
        self._pipeline_capable = (
            not (sc.get("wantRL", False) and not fused_carry) and
            not getattr(self.strategy, "host_rounds", False) and
            not getattr(self.strategy, "ef_rounds", False) and
            not (sc.server_replay_config is not None and
                 server_train_dataset is not None) and
            not wants_adaptive and
            not self._sample_hooked)
        # pipelined loops route the per-round `latest` save through the
        # async writer by default so serialization never blocks the next
        # dispatch; an explicit `checkpoint_async:` in the config wins.
        # NOTE the documented skew window (docs/RUNBOOK.md): under async
        # saves, status_log.json can run one round ahead of the on-disk
        # latest_model after a hard crash.
        ckpt_async = sc.get("checkpoint_async")
        if ckpt_async is None:
            ckpt_async = (self.pipeline_depth > 0 and
                          self._pipeline_capable and
                          str(sc.get("checkpoint_backend",
                                     "msgpack")) == "msgpack")
        self.ckpt = CheckpointManager(
            model_dir, backup_freq=sc.get("model_backup_freq", 100),
            backend=str(sc.get("checkpoint_backend", "msgpack")),
            async_latest=bool(ckpt_async),
            retry=RetryPolicy.from_config(sc.get("checkpoint_retry")),
            io_fault=(self.chaos.io_fault_hook if self.chaos is not None
                      else None))

        # ---- flutearmor: ONE durable-IO ladder for every host service
        # (resilience/integrity.py).  The same checkpoint_retry policy
        # that governs checkpoint saves now governs row-store spill/read,
        # the fleet round marker, the writeback fetch, and the rollup
        # writer — with per-surface escalators and the documented
        # degradation table; chaos.infra (when configured) supplies the
        # seeded per-surface fault hooks, so retries redraw fresh
        # decisions exactly like the checkpoint IO stream
        _infra = self.chaos.infra if self.chaos is not None else None
        _hooks = {}
        if _infra is not None:
            _hooks = {"store_write": _infra.hook("store_write"),
                      "store_read": _infra.hook("store_read"),
                      # the round marker is store-family durable IO: it
                      # shares the spill stream (one service, one tag)
                      "marker": _infra.hook("store_write"),
                      "writeback": _infra.hook("writeback"),
                      "writer": _infra.hook("writer")}
        self.ladder = DurableIOLadder(
            policy=RetryPolicy.from_config(sc.get("checkpoint_retry")),
            fault_hooks=_hooks)

        # ---- flutescope telemetry (server_config.telemetry) ----------
        # None when the block is absent/disabled — the default, and the
        # zero-cost contract: every instrumentation point below is one
        # is-None check, no spans, no tracer, no watchdog state
        # (tests/test_telemetry_contract.py).  When on, all host-side
        # consumption reads only values the loop ALREADY fetched (the
        # packed stats, wall clocks), so strict transfer mode and the
        # one-fetch-per-round guard hold unchanged.
        self.scope = make_telemetry(sc.get("telemetry"), model_dir)
        #: (device_kind, peak_flops) of the mesh's chip — the live-MFU
        #: denominator, resolved once (utils/compat.py chip table, CPU
        #: nominal fallback); None when the device-truth layer is off
        self._chip = None
        if self.engine.xla is not None:
            from ..utils.compat import chip_peak_flops
            self._chip = chip_peak_flops(next(iter(self.mesh.devices.flat)))
        if self.scope is not None:
            self.ckpt.telemetry = self.scope
            self.scope.watchdog.on_mark = self._watchdog_mark
            # flight-record context (ISSUE 13): the persisted forensic
            # snapshot embeds the run's scorecard, built at persist time
            self.scope.set_flight_context(self.build_scorecard)
            # a SIGTERM must make the trace/metrics durable BEFORE the
            # drain starts (the drain itself may wedge); the flight
            # record persists in the same window — if the drain then
            # wedges past the grace period, the black box is on disk
            self.preemption.add_flush_hook(self.scope.flush)
            self.preemption.add_flush_hook(self._flight_on_preempt)
        # every failed durable-IO attempt lands a structured
        # store_io_fault instant event (scope-less runs fall back to the
        # metrics stream), and the rollup writer itself degrades through
        # the ladder: an exhausted window append becomes the
        # rollup_windows_dropped event + counter, never an exception up
        # the host tail
        self.ladder.event = self._ladder_event
        if self.scope is not None and self.scope.rollup is not None:
            self.scope.rollup.ladder = self.ladder
            self.scope.rollup.on_drop = self._rollup_dropped

        # LR machinery: server-side schedule + client plateau decay
        self.initial_lr_client = float(sc.get("initial_lr_client", 0.01))
        self.lr_decay_factor = float(sc.get("lr_decay_factor", 1.0))
        self.lr_weight = 1.0
        self.server_lr_schedule = make_lr_schedule(
            sc.annealing_config, float(sc.optimizer_config.get("lr", 1.0)))
        self.plateau: Optional[PlateauTracker] = None
        if sc.annealing_config is not None and \
                sc.annealing_config.get("type") == "val_loss":
            self.plateau = PlateauTracker(
                sc.annealing_config, float(sc.optimizer_config.get("lr", 1.0)))

        self.best_model_criterion = sc.get("best_model_criterion", "loss")
        self.fall_back_to_best = bool(sc.get("fall_back_to_best_model", False))
        self.best_val: Dict[str, Metric] = {}

        # RL meta-aggregation (reference server_config.wantRL + extensions/RL)
        # — the HOST path (double-aggregate + val A/B + reward, three host
        # round trips).  Under fused_carry the tuner instead rides the
        # round program as device-resident carry (rl/fused.py): the engine
        # owns it and no host RLAggregator is built.
        self.rl = None
        if sc.get("wantRL", False) and not fused_carry:
            from ..rl import RLAggregator
            from ..config import RLConfig
            rl_cfg = sc.RL if sc.RL is not None else RLConfig.from_dict({})
            ncpi = sc.get("num_clients_per_iteration", 10)
            if not isinstance(ncpi, int):
                raise ValueError("wantRL requires a fixed "
                                 "num_clients_per_iteration")
            self.rl = RLAggregator(rl_cfg, ncpi, model_dir, seed=seed)
            self._rl_losses = None

        # privacy-attack metric bookkeeping (reference core/server.py:319-325)
        pm = config.privacy_metrics_config
        self.max_allowed_leakage: Optional[float] = None
        self.adaptive_leakage: Optional[float] = None
        if pm is not None and pm.get("apply_metrics", False):
            self.max_allowed_leakage = pm.get("max_allowed_leakage")
            adaptive = pm.get("adaptive_leakage_threshold")
            if adaptive:
                self.adaptive_leakage = float(adaptive)

        # static round-program geometry
        cc = config.client_config
        self.batch_size = int(cc.data_config.train.get("batch_size", 32))
        self.desired_max_samples = cc.get("desired_max_samples") or \
            cc.data_config.train.get("desired_max_samples")
        # np.max, not builtin max: the fleet path hands num_samples in
        # as a 10^6-entry int32 array, and builtin max would iterate it
        # element-by-element in the interpreter
        max_client_samples = int(np.max(np.asarray(
            train_dataset.num_samples)))
        self.max_steps = steps_for(max_client_samples, self.batch_size,
                                   self.desired_max_samples)
        # per-chunk step bucketing: size each fused chunk's [K, S, B] grid
        # to ITS sampled clients instead of the dataset-wide worst case —
        # padded steps are exact no-ops, so the math is unchanged (tested
        # bit-equal), but small-client rounds stop paying max-client FLOPs
        # and memory.  S rounds up to a power of two so jit retraces at
        # most log2(max_steps) distinct programs.
        self.step_bucketing = bool(cc.get("step_bucketing", True))
        # per-chunk LENGTH bucketing (token tasks): crop the [K,S,B,L]
        # grids' all-pad tail columns to a power-of-two bucket — the
        # static-shape answer to the reference DynamicBatchSampler's
        # padding-efficiency packing (utils/data_utils.py:42-119).  Math
        # identical (position masks come from the ids); host-packed path
        # only (the device pool stores full-length rows).
        self.length_bucketing = bool(
            cc.data_config.train.get("length_bucketing", True))
        self._length_bucket_stats = None
        # cohort shape-bucketing (server_config.cohort_bucketing): stop
        # padding every client to the slowest one.  The round's sampled
        # clients partition into a small config-bounded set of
        # power-of-two step buckets; each bucket packs its own compact
        # [K_b, S_b, B, ...] grid and the engine dispatches one collect
        # program per bucket + one on-device finalize per round
        # (engine/round.py).  Boundaries derive from the POPULATION's
        # step-need histogram once at init (greedy-merged to
        # max_buckets), or come from an explicit `boundaries:` list —
        # either way the S set is static, so compiled grid variants stay
        # bounded and the PR 7 recompile sentinel guards closure.
        self.cohort_bucketing = None
        self._step_needs = None
        _cb = sc.get("cohort_bucketing") or {}
        if _cb and _cb.get("enable", True):
            if host_orchestrated:
                raise ValueError(
                    "server_config.cohort_bucketing requires the fused "
                    "round path — wantRL (host), strategy: scaffold / "
                    "ef_quant (host rounds), and personalization's "
                    "overridden sampling orchestrate rounds host-side "
                    "and would silently run unbucketed; drop the block "
                    "or lift the strategy with fused_carry")
            from ..data.batching import bucket_boundaries
            from ..data.fleet import steps_for_array
            # one vectorized metadata pass over the population (fleet
            # scale: a 10^6-user pool must not pay an O(N) python loop
            # at server init)
            needs = steps_for_array(train_dataset.num_samples,
                                    self.batch_size,
                                    self.desired_max_samples)
            max_need = int(needs.max()) if needs.size else 1
            _mb = _cb.get("max_buckets")
            max_buckets = 4 if _mb is None else int(_mb)
            user_bounds = _cb.get("boundaries")
            if user_bounds:
                bounds = [int(b) for b in user_bounds]
                if any(b < 1 for b in bounds) or \
                        any(y <= x for x, y in zip(bounds, bounds[1:])):
                    raise ValueError(
                        "cohort_bucketing.boundaries must be strictly "
                        f"increasing positive ints, got {bounds}")
                # coverage: the TOP bucket must fit the biggest client's
                # step need or its data would silently truncate; user
                # boundaries above that only waste padded steps
                covering = [b for b in bounds if b >= max_need]
                top = min(covering[0] if covering else max_need,
                          self.max_steps)
                top = max(top, max_need)
                bounds = [b for b in bounds if b < top] + [top]
            else:
                bounds = bucket_boundaries(needs, max_buckets,
                                           self.max_steps)
            if len(bounds) > max_buckets:
                raise ValueError(
                    f"cohort_bucketing: {len(bounds)} boundaries exceed "
                    f"max_buckets={max_buckets} — raise max_buckets or "
                    "shorten the boundaries list")
            # static per-bucket capacities: every bucket grid dispatches
            # every round at its fixed K_b (occupied or not), so the
            # compiled shape set is exactly one collect program per
            # bucket + one finalize — closed by construction; overflow
            # spills up, top-bucket overflow (rare) enlarges that grid
            # and is exactly what the recompile sentinel exists to see
            from ..config import cohort_upper_bound
            from ..data.batching import bucket_capacities
            cohort_hi = min(cohort_upper_bound(
                sc.get("num_clients_per_iteration", 10)),
                len(train_dataset))
            caps = bucket_capacities(
                needs, bounds, cohort_hi,
                quantum=self.mesh.shape[CLIENTS_AXIS],
                slack=float(_cb.get("slack", 1.5) or 1.5))
            self.cohort_bucketing = {"boundaries": bounds,
                                     "capacities": caps,
                                     "max_buckets": max_buckets}
            self._step_needs = needs
            print_rank(
                f"cohort bucketing on: step buckets {bounds} with "
                f"client capacities {caps} (population max need "
                f"{max_need}, monolithic S {self.max_steps})")

        # cross-client megabatching (server_config.megabatch): static
        # per-bucket LANE counts from the same population histogram the
        # capacities came from — per-round tape planning happens in
        # _pack_bucketed_round, the segment-carrying lane scan in the
        # engine.  The engine __init__ already refused every
        # incompatible config (missing cohort_bucketing, privacy
        # metrics, pallas_apply, fedlabels), so this block only sizes
        # geometry when the cohort block is live.
        self.megabatch = None
        self._mega_slots = 0.0
        self._mega_real = 0.0
        _mgb = sc.get("megabatch") or {}
        if _mgb and _mgb.get("enable", True) and \
                self.cohort_bucketing is not None:
            from ..data.batching import megabatch_lanes
            _mgb_E = max(int(cc.get("num_epochs", 1) or 1), 1)
            mgb_lanes = megabatch_lanes(
                self._step_needs, bounds, cohort_hi, _mgb_E,
                quantum=self.mesh.shape[CLIENTS_AXIS],
                slack=float(_mgb.get("slack", 1.25) or 1.25),
                lanes=_mgb.get("lanes"), caps=caps)
            self.megabatch = {
                "lanes": mgb_lanes, "epochs": _mgb_E,
                "min_gain": float(_mgb.get("min_gain", 0.1) or 0.0),
            }
            print_rank(
                f"megabatch on: per-bucket lanes {mgb_lanes} over step "
                f"buckets {bounds} (tape depth = {_mgb_E} x S_b, "
                f"min_gain {self.megabatch['min_gain']})")

        # device-resident dataset (data_config.train.device_resident): the
        # whole sample pool lives in HBM; rounds ship [K,S,B] int32 indices
        # and the row gather runs inside the compiled round program.
        # Requires the dataset to fit in memory (build_sample_pool).
        self._pool_offsets = None
        if bool(cc.data_config.train.get("device_resident", False)):
            if self.rl is not None or \
                    getattr(self.strategy, "host_rounds", False) or \
                    getattr(self.strategy, "ef_rounds", False):
                # RL / SCAFFOLD / EF rounds go through the host payload
                # path, which never consults the pool — uploading the
                # dataset to HBM would cost memory for zero benefit,
                # silently
                raise ValueError(
                    "data_config.train.device_resident does not apply to "
                    "host-orchestrated rounds (wantRL / strategy: "
                    "scaffold / strategy: ef_quant) — drop the flag for "
                    "this configuration")
            from ..data.batching import build_sample_pool
            pool_np, self._pool_offsets = build_sample_pool(train_dataset)
            self.engine.attach_pool(pool_np)
            del pool_np

        # server replay training (reference core/server.py:429-442): after
        # aggregation, train on server-held data for a few iterations
        self.server_replay = None
        if sc.server_replay_config is not None and \
                server_train_dataset is not None:
            if getattr(self.strategy, "owns_server_update", False):
                raise ValueError(
                    f"{type(self.strategy).__name__} maintains coupled "
                    "parameter sequences; server replay would mutate params "
                    "behind its back — disable server_replay_config")
            self.server_replay = {
                "dataset": server_train_dataset,
                "iterations": int(sc.server_replay_config.get(
                    "server_iterations", 1)),
                "opt_cfg": sc.server_replay_config.optimizer_config,
                # regex allowlist of layers to update during replay
                # (reference set_component_wise_lr, core/trainer.py:725-751)
                "updatable_names": sc.server_replay_config.get(
                    "updatable_names"),
            }

        # quantization threshold annealing (reference core/server.py:294-298)
        self.quant_thresh = cc.get("quant_thresh") or             config.model_config.get("quant_threshold")
        self.quant_anneal = float(cc.get("quant_anneal", 1.0) or 1.0)

        # flag-gated profiling (reference server/client do_profiling flags,
        # core/schema.py:84,233) — emits a TensorBoard-readable XLA trace
        self._profile_dir = None
        self._chunks_run = 0
        if sc.get("do_profiling", False) or cc.get("do_profiling", False):
            self._profile_dir = os.path.join(model_dir, "profile")

        self._eval_fn = build_eval_fn(task, self.mesh,
                                      self.engine.partition_mode)
        if self.engine.xla is not None:
            # device-truth capture for the eval program too: its
            # FLOPs/HBM row joins the scorecard's entry-point table and
            # an eval-grid shape churn trips the same recompile sentinel
            self._eval_fn = self.engine.xla.wrap("eval_step",
                                                 self._eval_fn)
        self._eval_batches_cache: Dict[str, Any] = {}
        self._per_user_fns: Dict[str, Any] = {}
        self._np_rng = np.random.default_rng(seed)
        # device-side randomness: a CONSTANT base key + a host-side use
        # counter; every consumer takes fold_in(base, n) via _next_rng().
        # The counter (not the key) is what resume persists — restoring
        # it re-anchors every later stream bit-exactly WITHOUT fetching
        # key material from the device (which would add a host transfer
        # per round to the pipelined loop's single-fetch contract).
        self._rng = jax.random.PRNGKey(seed)
        self._rng_uses = 0
        self.run_stats: Dict[str, list] = {
            "secsPerRound": [], "secsPerRoundHousekeeping": [],
            "secsPerRoundHostTail": [], "hostToDeviceBytesPerRound": [],
            # live MFU (device-truth layer: compiled FLOPs / round
            # wall-clock / chip peak) — populated only when
            # telemetry.xla captured the round program's cost
            "mfuPerRound": [],
            # real samples / padded grid slots per packed chunk — the
            # cohort-bucketing win, measured on EVERY run (monolithic
            # too, so the bench A/B and scope diff can compare)
            "paddingEfficiency": []}
        #: run-total padding-efficiency accumulators (slots-weighted —
        #: see _record_padding_efficiency)
        self._pad_real = 0.0
        self._pad_slots = 0
        #: chunks whose host tail overlapped the next chunk's device
        #: execution (observability + the equivalence tests' proof that
        #: the pipelined run actually pipelined)
        self.pipelined_chunks = 0

        self.state = self.engine.init_state(self._rng)
        pretrained = config.model_config.get("pretrained_model_path")
        if pretrained:
            from .checkpoint import load_pretrained_params
            params = load_pretrained_params(pretrained, self.state.params,
                                            data_path=config.data_path)
            # warm-started params, fresh optimizer/strategy state, round 0
            # (reference loads the model before training, e2e_trainer.py:104);
            # keep each leaf on the sharding init_state chose for it
            params = jax.tree.map(
                lambda host, old: jax.device_put(
                    jnp.asarray(host, old.dtype), old.sharding),
                params, self.state.params)
            # strategy state re-derives from the WARM params (e.g. FedAC's
            # w_ag sequence must start at the pretrained point, not the
            # discarded random init)
            self.state = ServerState(params, self.state.opt_state,
                                     self.strategy.init_state(params), 0)
            print_rank(f"warm-started from pretrained model {pretrained}")
        resumed = False
        self._status_ring: list = []
        if sc.get("resume_from_checkpoint", False):
            restored = self.ckpt.load(self.state)
            if restored is not None and self._fleet_paged:
                restored = self._paired_fleet_anchor(restored, model_dir)
            if restored is not None:
                self.state = self._place_restored(restored, self.state)
                resumed = True
                status = self._paired_status(self.ckpt.read_status(),
                                             int(self.state.round))
                # continue the per-round anchor ring from the resumed
                # round; entries beyond it belong to the dead trajectory
                # and get rewritten by the replay
                self._status_ring = [
                    e for e in status.get("status_ring", [])
                    if int(e[0]) <= int(self.state.round)]
                self.lr_weight = float(status.get("weight", 1.0))
                # re-anchor the RNG streams (client sampling order + the
                # device-key counter) so the post-resume trajectory is
                # bit-identical to an uninterrupted run — the core of the
                # preemption contract (tests/test_preempt_resume.py)
                self._restore_rng(status)
                # plateau-LR tracker + best-val metrics live only in
                # memory; restore them so the post-resume LR schedule and
                # best-checkpoint decisions re-anchor too
                if self.plateau is not None and "plateau" in status:
                    pl = status["plateau"]
                    self.plateau.lr = float(pl.get("lr", self.plateau.lr))
                    self.plateau.best = pl.get("best")
                    self.plateau.bad_rounds = int(pl.get("bad_rounds", 0))
                hib = status.get("best_val_hib", {})
                for key, value in status.items():
                    if key.startswith("best_val_") and key != "best_val_hib" \
                            and isinstance(value, (int, float)):
                        name = key[len("best_val_"):]
                        self.best_val[name] = Metric(
                            float(value), bool(hib.get(name, name != "loss")))
                print_rank(f"resumed from checkpoint at round {self.state.round}")
                # fast-forward the quantization-threshold annealing to the
                # resumed round: the schedule is a pure geometric series
                # (thresh_R = thresh_0 * anneal^R), but the running value
                # lives only in memory — without this, a resume restarts
                # the anneal from the config value and the post-resume
                # trajectory diverges from an uninterrupted run (both the
                # fused path's self.quant_thresh and the EF strategy's own
                # copy, strategies/ef_quant.py::next_threshold)
                if self.state.round > 0 and self.quant_anneal != 1.0:
                    ff = self.quant_anneal ** self.state.round
                    if self.quant_thresh is not None:
                        self.quant_thresh = float(self.quant_thresh) * ff
                    if getattr(self.strategy, "ef_rounds", False):
                        self.strategy.quant_thresh *= ff

        # SCAFFOLD control variates (strategies/scaffold.py): host-side
        # store under the model dir.  Controls are reloaded ONLY when the
        # model checkpoint itself resumed — params and controls belong to
        # the same trajectory; a fresh run wipes any previous run's files.
        self.scaffold_store = None
        if getattr(self.strategy, "host_rounds", False):
            from ..strategies.scaffold import ControlStore
            n_params = sum(int(np.prod(l.shape))
                           for l in jax.tree.leaves(self.state.params))
            self.scaffold_store = ControlStore(
                n_params, store_dir=os.path.join(model_dir, "scaffold"),
                resume=resumed)
            if resumed and self.scaffold_store.round() != self.state.round:
                # control writes are synchronous but the model checkpoint
                # may be async: a crash can leave controls ahead of the
                # restored params.  Mismatched trajectories must not mix —
                # restart control estimation from zero.
                print_rank(
                    f"SCAFFOLD controls were at round "
                    f"{self.scaffold_store.round()} but the checkpoint "
                    f"resumed at {self.state.round}; resetting controls")
                self.scaffold_store.reset()
        # error-feedback quantization residuals (strategies/ef_quant.py):
        # same durable per-client row-store discipline as the SCAFFOLD
        # controls — residuals belong to the checkpoint's trajectory
        self.ef_store = None
        if getattr(self.strategy, "ef_rounds", False):
            from ..strategies.ef_quant import ResidualStore
            n_params = sum(int(np.prod(l.shape))
                           for l in jax.tree.leaves(self.state.params))
            self.ef_store = ResidualStore(
                n_params, store_dir=os.path.join(model_dir, "ef_residuals"),
                resume=resumed)
            if resumed and self.ef_store.round() != self.state.round:
                # residual writes are synchronous but the checkpoint may
                # land later (async orbax): mismatched trajectories reset
                # (same marker semantics as the SCAFFOLD controls)
                print_rank(
                    f"EF residuals were at round {self.ef_store.round()} "
                    f"but the checkpoint resumed at {self.state.round}; "
                    "resetting residuals")
                self.ef_store.reset()

        # device-resident control table (scaffold_device_controls): keep
        # the whole [N, n_params] table in HBM; gather offsets and scatter
        # the option-II update in-program so no model-sized per-round
        # transfer crosses the host boundary (strategies/scaffold.py
        # DeviceControlTable).  Built AFTER the resume/reset decision so
        # the table warms up from exactly the controls the run keeps.
        self.scaffold_device = None
        if sc.get("scaffold_device_controls", False):
            if self.scaffold_store is None:
                raise ValueError(
                    "server_config.scaffold_device_controls requires "
                    "strategy: scaffold — with "
                    f"{type(self.strategy).__name__} there are no "
                    "controls to keep on device; drop the flag")
            from ..strategies.scaffold import DeviceControlTable
            self.scaffold_device = DeviceControlTable(
                self.scaffold_store, len(train_dataset), self.mesh)
            gb = 4.0 * self.scaffold_device.n_rows * \
                self.scaffold_store.n_params / 2**30
            print_rank(f"SCAFFOLD device control table: "
                       f"{self.scaffold_device.n_rows} x "
                       f"{self.scaffold_store.n_params} ({gb:.2f} GiB HBM)")

        # device-resident EF residual table (ef_device_residuals): same
        # transfer-vs-HBM tradeoff as the SCAFFOLD table — the per-round
        # [K, n_params] residual matrix stops crossing the host boundary
        # in either direction (strategies/ef_quant.py DeviceResidualTable).
        # Built AFTER the resume/reset decision so it warms from exactly
        # the residuals the run keeps.
        self.ef_device = None
        if sc.get("ef_device_residuals", False):
            if self.ef_store is None:
                raise ValueError(
                    "server_config.ef_device_residuals requires "
                    "strategy: ef_quant — with "
                    f"{type(self.strategy).__name__} there are no "
                    "residuals to keep on device; drop the flag")
            from ..strategies.ef_quant import DeviceResidualTable
            self.ef_device = DeviceResidualTable(
                self.ef_store, len(train_dataset), self.mesh)
            gb = 4.0 * self.ef_device.n_rows * \
                self.ef_store.n_params / 2**30
            print_rank(f"EF device residual table: "
                       f"{self.ef_device.n_rows} x "
                       f"{self.ef_store.n_params} ({gb:.2f} GiB HBM)")

        # fleet paged carry (server_config.fleet + fused_carry): the
        # page pool + host backing store behind the carry tables.
        # Built AFTER the resume decision so the durable row store and
        # the restored params stay on one trajectory (the ControlStore
        # marker discipline) — a marker/round mismatch resets the rows.
        self.fleet_pager = None
        if self._fleet_paged:
            from .paging import CarryPager
            if resumed:
                # the restored tables came off the checkpoint as host
                # arrays: first re-derive the slot geometry for THIS
                # mesh (mesh-elastic resume — a checkpoint saved on M
                # shards may restore [P_old] tables), then re-lay them
                # out with the slot axis sharded so the donated round
                # program sees the SAME layout a fresh init builds (no
                # resharding copy, no donation churn)
                self.state = ServerState(
                    self.state.params, self.state.opt_state,
                    self.engine.shard_carry_state(
                        self._elastic_carry_tables(
                            self.state.strategy_state)),
                    self.state.round)
            self.fleet_pager = CarryPager(
                self.strategy, self.state.strategy_state,
                slots=int(self.strategy.carry_rows), mesh=self.mesh,
                store_dir=os.path.join(model_dir, "fleet_carry"),
                host_cache_rows=int(
                    self._fleet_cfg.get("host_cache_rows", 8192) or 8192),
                resume=resumed,
                partition_mode=self.engine.partition_mode,
                prefetch=bool(self._fleet_cfg.get("prefetch", True)),
                ladder=self.ladder,
                faults=(self.chaos.infra if self.chaos is not None
                        else None))
            # the prefetch worker spans its host IO on its own thread
            # track — the trace then SHOWS the paging stage overlapping
            # the device window instead of on the critical path
            self.fleet_pager.scope = self.scope
            if resumed:
                marker = self.fleet_pager.round()
                if marker is None or int(marker) < int(self.state.round):
                    # unreachable when the anchor pairing above chose
                    # the slot, but direct dir surgery / legacy stores
                    # still get the one-trajectory safety net
                    print_rank(
                        f"fleet carry rows were at round {marker} but "
                        f"the checkpoint resumed at {self.state.round}; "
                        "resetting carry rows (one-trajectory rule)")
                    self.fleet_pager.reset()
                else:
                    # prune the dead trajectory's newer row generations
                    # (a marker AHEAD of the anchor is fine: those
                    # generations are exactly what adoption removes)
                    self.fleet_pager.adopt_round(int(self.state.round))
                    self.fleet_pager.mark_durable(
                        int(self.state.round) - 1)
            mb = (self.fleet_pager.n_slots *
                  self.fleet_pager.hbm_row_bytes()) / 2**20
            print_rank(
                f"fleet paged carry: {self.fleet_pager.n_slots} pool "
                f"slots x {sorted(self.strategy.carry_tables)} "
                f"({mb:.1f} MiB HBM total, "
                f"{mb / self.fleet_pager.mesh_shards:.1f} MiB/device "
                f"over {self.fleet_pager.mesh_shards} shards) over "
                f"{len(train_dataset)} clients")

    # ------------------------------------------------------------------
    def _select_strategy(self, config) -> type:
        """The strategy class this server will construct.  Subclasses
        whose behavior moved into a device-carry strategy under
        ``fused_carry`` override this (PersonalizationServer swaps in
        PersonalizedFedAvg); the base server keeps the registry lookup."""
        return select_strategy(config.strategy)

    # ------------------------------------------------------------------
    def _tspan(self, name: str, **args):
        """One flutescope span — the shared no-op context when telemetry
        is off (the off path costs one attribute read + None check)."""
        return self.scope.span(name, **args) if self.scope is not None \
            else NULL_SPAN

    def _watchdog_mark(self, kind: str, fields: Dict[str, Any]) -> None:
        """Watchdog ``mark`` action: persist the finding to the status
        log so a post-mortem sees it without the metrics stream."""
        self.ckpt.update_status({f"watchdog_{kind}": dict(fields)})

    def _ladder_event(self, kind: str, **fields: Any) -> None:
        """The durable-IO ladder's structured-event sink (scope or the
        bare metrics stream — emit_event handles both)."""
        emit_event(self.scope, kind, **fields)

    def _rollup_dropped(self, rec: Dict[str, Any]) -> None:
        """Rollup-writer exhaustion callback: the degradation table's
        telemetry leg — count it, surface it, keep training."""
        dropped = (self.scope.rollup.windows_dropped
                   if self.scope is not None and
                   self.scope.rollup is not None else 1)
        emit_event(self.scope, "rollup_windows_dropped",
                   windows_dropped=int(dropped),
                   window=rec.get("window"))

    def _place_restored(self, restored: Any, template: Any) -> Any:
        """Re-place a checkpoint-restored state on the shardings
        ``init_state`` chose (the pretrained-path idiom): restore hands
        back HOST numpy leaves, and dispatching those raw commits a
        second input layout — the first post-resume chunk would compile
        a warmup variant that differs from steady state (a spurious
        recompile on every resume).  Leaves whose SHAPE changed (a
        mesh-elastic resume's slot-sized carry tables) stay host-side:
        the fleet path rebuilds and re-shards them explicitly.  Only
        MESH shardings are re-placed: a template leaf sitting on a
        SingleDeviceSharding is an UNCOMMITTED jnp-op result whose
        placement was incidental (jit moves it freely), and committing
        the restored copy there via device_put would pin it to one
        device next to committed mesh-sharded params — an
        incompatible-devices dispatch error.  Those leaves come back as
        uncommitted host numpy, the layout the fresh init dispatches."""
        from jax.sharding import SingleDeviceSharding
        def leaf(host, old):
            sh = getattr(old, "sharding", None)
            if sh is None or isinstance(sh, SingleDeviceSharding) or \
                    np.shape(host) != tuple(old.shape):
                return np.asarray(jax.device_get(host))
            return jax.device_put(jnp.asarray(host, old.dtype), sh)
        from .round import ServerState
        return ServerState(
            params=jax.tree.map(leaf, restored.params, template.params),
            opt_state=jax.tree.map(leaf, restored.opt_state,
                                   template.opt_state),
            strategy_state=jax.tree.map(leaf, restored.strategy_state,
                                        template.strategy_state),
            round=restored.round)

    def _paired_fleet_anchor(self, restored: Any, model_dir: str) -> Any:
        """Crash-consistent resume anchor under fleet paging
        (flutearmor crash-point contract): the carry marker commits
        AFTER the model checkpoint, so a hard kill inside a round's
        commit window can leave ``latest_model`` ahead of the durable
        row set (pipelined loops save each chunk's latest at the NEXT
        dispatch, widening the window to the ring depth).  Bit-identical
        resume requires params and carry from the SAME round, so the
        anchor is the round the MARKER proves durable: keep latest when
        it matches (or trails — newer row generations prune away), fall
        back to the ``.prev`` slot when THAT matches, and otherwise
        cold-start — the seeded run replays from round 0 to the same
        bits, trading wall clock for correctness."""
        from .paging import read_marker
        marker = read_marker(os.path.join(model_dir, "fleet_carry"))
        durable = int(marker) if marker is not None else 0
        latest_round = int(restored.round)
        if durable >= latest_round:
            return restored
        from .checkpoint import LATEST_PREV
        prev = self.ckpt.load(self.state, LATEST_PREV)
        if prev is not None and int(prev.round) == durable:
            print_rank(
                f"fleet carry rows are durable through round {durable} "
                f"but latest_model is at {latest_round} (hard stop "
                "inside the commit window); resuming from the previous "
                "slot so params and carry stay on one trajectory")
            return prev
        print_rank(
            f"fleet carry rows are durable through round {durable} with "
            f"no matching checkpoint slot (latest {latest_round}); "
            "cold-starting — the seeded replay reproduces the run "
            "bit-for-bit")
        return None

    def _paired_status(self, status: Dict[str, Any],
                       round_no: int) -> Dict[str, Any]:
        """The status snapshot PAIRED with the resumed round: the
        status log is written before the round's checkpoint commits
        (and an async save can land later still), so after a hard kill
        the flat fields may belong to a nearby round.  The per-round
        anchor ring keeps the last few snapshots; re-anchoring from the
        checkpoint's own entry keeps the replayed sampling trail — and
        the LR/plateau/best-val trajectory — bit-identical.  Logs
        without a ring (or a ring that rolled past the anchor) fall
        back to the flat fields, the historical behaviour."""
        for entry in reversed(status.get("status_ring", [])):
            if int(entry[0]) == int(round_no):
                merged = dict(status)
                merged.update(entry[1])
                return merged
        return status

    def _elastic_carry_tables(self, strategy_state: Any) -> Any:
        """Mesh-elastic resume (flutearmor leg 4): a fleet checkpoint
        saved on M shards restores carry tables sized for the OLD
        mesh's quantized pool; this run's pool (``strategy.carry_rows``,
        re-quantized for the NEW mesh at construction) may differ.
        Slot-sized tables rebuild at the new capacity from the carry
        defaults — sound because resumed slot maps start EMPTY and the
        host row store (shard-agnostic, keyed by global client id) is
        the authoritative row source: every next touch pages the true
        row in, so per-client math never sees the rebuilt defaults.
        The sampling trail replays via the regular RNG re-anchoring —
        final params stay bit-identical to the uninterrupted run
        (tests/test_fleet_mesh.py)."""
        new_slots = int(self.strategy.carry_rows)
        defaults = dict(self.strategy.carry_row_defaults())
        rebuilt = {}
        old_slots = None
        for k in self.strategy.carry_tables:
            leaf = strategy_state[k]
            rows = int(leaf.shape[0])
            if rows == new_slots:
                continue
            old_slots = rows
            rebuilt[k] = np.full(
                (new_slots,) + tuple(int(d) for d in leaf.shape[1:]),
                defaults.get(k, 0.0), dtype=np.dtype(str(leaf.dtype)))
        if not rebuilt:
            return strategy_state
        emit_event(self.scope, "elastic_resume",
                   from_slots=int(old_slots), to_slots=new_slots,
                   mesh_shards=int(self.mesh.shape[CLIENTS_AXIS]),
                   tables=sorted(rebuilt))
        print_rank(
            f"mesh-elastic resume: carry pool re-quantized "
            f"{old_slots} -> {new_slots} slots for the "
            f"{int(self.mesh.shape[CLIENTS_AXIS])}-shard mesh; rows "
            "reload from the host store on first touch")
        new_state = dict(strategy_state)
        new_state.update(rebuilt)
        return new_state

    def _flight_on_preempt(self) -> None:
        """Preemption flush hook: persist the flight record as part of
        the pre-drain durability window (runs OUTSIDE signal context,
        at the round loop's poll — the deferred-flush discipline)."""
        self.scope.record_flight(
            f"preemption: {self.preemption.reason or 'requested'}")

    # ------------------------------------------------------------------
    def _next_rng(self) -> jax.Array:
        """The run's next device RNG stream: ``fold_in(base, n)`` with a
        host-side monotone counter.  Deterministic in EVENT ORDER (which
        the config fixes), and resumable by persisting the single int —
        see ``_rng_snapshot``."""
        key = jax.random.fold_in(self._rng, self._rng_uses)
        self._rng_uses += 1
        return key

    def _rng_snapshot(self) -> Dict[str, Any]:
        """Host-RNG resume anchor: the numpy bit-generator state (client
        sampling + packing shuffles) and the device-key use counter.
        MUST be captured after all randomness attributable to the
        checkpointed rounds is drawn and before any later round draws —
        the caller picks the point (dispatch time when lookahead packing
        overlaps, housekeeping time otherwise)."""
        import copy
        return {
            "np_rng_state": copy.deepcopy(self._np_rng.bit_generator.state),
            "rng_uses": int(self._rng_uses),
        }

    def _restore_rng(self, status: Dict[str, Any]) -> None:
        """Re-anchor both RNG streams from a status-log snapshot (absent
        in pre-resilience status logs -> streams restart, matching the
        old resume behavior)."""
        if "np_rng_state" in status:
            self._np_rng.bit_generator.state = status["np_rng_state"]
        if "rng_uses" in status:
            self._rng_uses = int(status["rng_uses"])

    # ------------------------------------------------------------------
    def _sample(self) -> list:
        if self.traffic is not None:
            # fluteflow: the arrival plane decides WHO trains — the
            # cohort is the fire's buffer contents, replayed from the
            # seeded timeline (deterministic in fire order, so serial ==
            # pipelined == prefetched == resumed).  The numpy sampling
            # trail is untouched: a traffic run is a different trail by
            # construction, like a fleet sampling mode.
            r = self._traffic_round
            self._traffic_round = r + 1
            fire = self.traffic.fire(r)
            emit_event(self.scope, "buffer_fired", round=r,
                       tick=int(fire["tick"]),
                       wait_ticks=int(fire["wait_ticks"]),
                       stale_max=int(fire["staleness"].max(initial=0)),
                       stale_sum=int(fire["staleness"].sum()))
            return [int(c) for c in fire["cohort"]]
        sc = self.config.server_config
        n = parse_clients_per_round(sc.get("num_clients_per_iteration", 10),
                                    self._np_rng)
        n = min(n, len(self.train_dataset))
        fleet_mode = (str(self._fleet_cfg.get("sampling", "uniform"))
                      if self._fleet_cfg is not None else "uniform")
        if fleet_mode != "uniform":
            # fleet cohort draw (data/fleet.py): explicit Floyd /
            # weighted-reservoir sampling.  NOTE the rng-trail contract
            # (docs/config_extensions.md): these modes draw a NEW
            # sampling trail — like changing the seed — while staying
            # deterministic and resume-stable within it.  The default
            # `uniform` mode keeps the numpy draw below, so plain fleet
            # runs stay trail- (and bit-) identical to non-fleet runs.
            from ..data.fleet import sample_cohort
            return sample_cohort(
                self._np_rng, len(self.train_dataset), n,
                mode=fleet_mode,
                num_samples=self.train_dataset.num_samples)
        # random.sample equivalent (core/server.py:300-302).  Already
        # O(cohort) at any population size: numpy's Generator.choice
        # with replace=False uses Floyd's algorithm (time and memory
        # scale with `size`, not the population — pinned by
        # tests/test_fleet.py::test_default_cohort_draw_is_o_cohort),
        # so the default path keeps its historical rng trail even at
        # 10^6+ clients.
        return list(self._np_rng.choice(len(self.train_dataset), size=n,
                                        replace=False))

    # ------------------------------------------------------------------
    def run(self) -> ServerState:
        return self.train()

    def train(self) -> ServerState:
        # graceful-preemption window: SIGTERM/SIGINT during the loop flip
        # the handler's flag (polled at chunk boundaries) instead of
        # killing the process mid-round; previous dispositions are
        # restored on the way out
        self.preempted = False
        self.preemption.reset()  # a past preemption must not latch forever
        self.preemption.install()
        if self.traffic is not None:
            # a resumed run replays the identical fire sequence: the
            # timeline is a pure function of the traffic seed, so this
            # is a cache warm-up, not a state restore
            self._traffic_round = int(self.state.round)
            self.traffic.fast_forward(self._traffic_round)
        if self.scope is not None:
            # stall monitor (ISSUE 13): a named daemon thread polling
            # the round-completion heartbeat — spawned only when
            # telemetry.watchdog.stall_action is not "off"
            self.scope.watchdog.start_stall_monitor()
        try:
            # strict transfer mode (MSRFLUTE_STRICT_TRANSFERS=1,
            # fluteguard's runtime half): the whole round loop — fused,
            # pipelined, and the host-orchestrated RL/SCAFFOLD/EF paths —
            # runs with implicit device->host transfers disallowed; the
            # explicit device_get fetches (packed stats, eval, host
            # tails) are the only sanctioned crossings.  No-op without
            # the env flag.
            with strict_transfer_scope():
                return self._train_loop()
        except BaseException as exc:
            # a mid-loop abort (WatchdogAbort, checkpoint escalation,
            # Ctrl-C) skips _train_loop's normal tail: await in-flight
            # async checkpoint saves so the resume anchor is not missing
            # rounds — best-effort, never masking the original abort
            try:
                self.ckpt.wait()
            except Exception:
                pass
            if self.scope is not None:
                # the flight record IS the abnormal exit's deliverable:
                # last-N events + live rollup window + scorecard,
                # persisted atomically before the stack unwinds further
                try:
                    self.scope.record_flight(
                        f"exception: {type(exc).__name__}",
                        detail=str(exc))
                except Exception:
                    pass
            raise
        finally:
            if self.scope is not None:
                self.scope.watchdog.stop_stall_monitor()
                if self.scope.rollup is not None:
                    # the trailing partial window still holds up to
                    # window-1 rounds of trend data — flush it so the
                    # on-disk rollup stream covers the whole run
                    try:
                        self.scope.rollup.flush_window(partial=True)
                    except Exception:
                        pass
            if self.scope is not None:
                # the trace of an ABORTED run is exactly the trace the
                # operator needs; close any open profiler window and
                # materialize trace.json whatever path exited the loop
                self.scope.profiler.finish()
                try:
                    # compile/recompile events buffered after the last
                    # drain (e.g. an eval compile) land in the streams,
                    # THEN the trace flushes, THEN the scorecard is
                    # built (its overlap numbers read the flushed
                    # trace).  An aborted run keeps its scorecard too —
                    # that is the run `tools/scope diff` most needs.
                    self._drain_xla_events()
                    self.scope.flush()
                    self.scope.write_scorecard(self.build_scorecard())
                except Exception:
                    pass
            self.preemption.uninstall()

    def _train_loop(self) -> ServerState:
        sc = self.config.server_config
        max_iteration = int(sc.get("max_iteration", 100))
        # single source of truth for "is this the final round" decisions
        # made later in _round_housekeeping (scaffold flush cadence)
        self._max_iteration = max_iteration
        val_freq = int(sc.get("val_freq", 20) or 20)
        rec_freq = int(sc.get("rec_freq", 20) or 20)

        if self.state.round == 0 and sc.get("initial_val", True):
            self._maybe_eval("val", self.state.round, force=True)
        if self.state.round == 0 and sc.get("initial_rec", False):
            self._maybe_eval("test", self.state.round, force=True)

        # TPU-native knob (no reference equivalent): how many rounds to fuse
        # into one scanned device program.  1 == FLUTE-style per-round
        # dispatch; larger values amortize host<->device latency.  Chunks
        # never cross an eval boundary, so plateau/LR/fallback semantics are
        # unchanged.
        rounds_per_step = max(int(sc.get("rounds_per_step", 1) or 1), 1)

        if self.rl is not None:
            rounds_per_step = 1  # RL needs val feedback every round
        if self.scaffold_store is not None:
            # control gather/update is per-round host work (like the
            # reference's per-round protocol exchange); no chunk fusion
            rounds_per_step = 1
        if self.server_replay is not None and rounds_per_step > 1:
            # reference runs replay after EVERY round (core/server.py:429);
            # fusing rounds would cut the replay cadence
            print_rank("server replay forces rounds_per_step=1")
            rounds_per_step = 1
        # which chunk to profile: the second (post-compile) when there will
        # be more than one, else the only one
        profile_chunk = (0 if max_iteration - self.state.round <=
                         rounds_per_step else 1)

        def chunk_R(r0: int) -> int:
            until_val = (val_freq - (r0 % val_freq)
                         if self.val_dataset is not None else max_iteration)
            until_rec = (rec_freq - (r0 % rec_freq)
                         if self.test_dataset is not None else max_iteration)
            return min(rounds_per_step, max_iteration - r0,
                       until_val, until_rec)

        def pack_chunk(R: int) -> list:
            with self._tspan("pack", rounds=R):
                return _pack_chunk_inner(R)

        def _pack_chunk_inner(R: int) -> list:
            # sample the whole chunk first so every round pads to a common
            # client count (ranged num_clients_per_iteration draws differ)
            chunk_samples = [self._sample() for _ in range(R)]
            if self.cohort_bucketing is not None:
                # nested layout: batches[r] is round r's list of
                # per-bucket grids (ascending bucket order)
                batches = [self._pack_bucketed_round(sampled)
                           for sampled in chunk_samples]
                flat = [b for row in batches for b in row]
                self._maybe_length_bucket(flat)
                self._record_padding_efficiency(flat)
                return batches
            pad_to = pad_to_mesh(max(len(s) for s in chunk_samples),
                                 self.mesh)
            steps = self._chunk_steps(chunk_samples)
            if self._pool_offsets is not None:
                from ..data.batching import pack_round_indices
                batches = [pack_round_indices(
                    self.train_dataset, self._pool_offsets, sampled,
                    self.batch_size, steps, rng=self._np_rng,
                    pad_clients_to=pad_to,
                    desired_max_samples=self.desired_max_samples)
                    for sampled in chunk_samples]
                self._record_padding_efficiency(batches)
                return batches
            batches = [pack_round_batches(
                self.train_dataset, sampled, self.batch_size, steps,
                rng=self._np_rng, pad_clients_to=pad_to,
                desired_max_samples=self.desired_max_samples)
                for sampled in chunk_samples]
            self._maybe_length_bucket(batches)
            self._record_padding_efficiency(batches)
            return batches

        # prefetch: with fused chunks, the NEXT chunk's host-side sampling
        # and packing happen right after this chunk's async dispatch, so the
        # numpy work overlaps device execution instead of serializing with
        # it.  Disabled when anything host-side runs between chunks that
        # could interact with sampling/packing order (RL, server replay —
        # both force rounds_per_step=1 anyway — and subclasses that hook
        # ``_sample`` against the live global model, e.g. personalization).
        prefetch_ok = (rounds_per_step > 1 and self.rl is None and
                       self.server_replay is None and
                       type(self)._sample is OptimizationServer._sample)
        prefetched = None  # (R, batches) for the upcoming round_no

        # pipelined mode subsumes prefetch: packing ALREADY overlaps the
        # device because the whole host tail is deferred past dispatch
        pipelined = self.pipeline_depth > 0 and self._pipeline_ok()
        if pipelined:
            prefetch_ok = False
        # fleet row prefetch: stage the NEXT chunk's missing carry rows
        # (host-store IO) on the pager's worker thread while this
        # chunk executes, so the page-in's host half leaves the
        # critical path.  Needs lookahead packing — the same sampling-
        # order discipline prefetch_ok already guards (the rng draw
        # order is unchanged: cohorts are data-independent lookahead).
        fleet_prefetch = (self.fleet_pager is not None and
                          self.fleet_pager.prefetch_enabled and
                          self.rl is None and self.server_replay is None
                          and not self._sample_hooked)
        lookahead_pack = prefetch_ok or (pipelined and fleet_prefetch)
        # the ring of dispatched-but-undrained chunks, oldest first: up to
        # ``pipeline_depth`` stay in flight; each dispatch drains the
        # oldest once the ring is full, so with depth N the host tail of
        # chunk k overlaps the device execution of chunks k+1..k+N
        pending: deque = deque()
        self._last_fence = 0.0

        round_no = self.state.round
        start_round = round_no
        while round_no < max_iteration:
            # preemption poll (chunk granularity): a SIGTERM between
            # chunks, or the chaos drill's preempt_at_round, stops BEFORE
            # dispatching new device work; the in-flight pending chunk is
            # drained after the loop so its rounds are kept, checkpointed,
            # and the exit is resumable.  The drill fires only when this
            # run CROSSES the threshold from below — a resumed run that
            # starts at/past it (the RUNBOOK drill relaunches with the
            # same config) trains on instead of re-preempting forever.
            if (self.chaos is not None and
                    self.chaos.preempt_at_round is not None and
                    start_round < self.chaos.preempt_at_round <= round_no
                    and not self.preemption.requested):
                self.preemption.request(
                    f"chaos preempt_at_round="
                    f"{self.chaos.preempt_at_round}")
            if self.preemption.requested:
                # a signal-context request deferred its observability
                # flush (file IO is unsafe in a handler); run it here,
                # outside signal context, BEFORE the drain starts
                self.preemption.flush_now()
                break
            tic = time.time()
            R = chunk_R(round_no)
            if self.scope is not None:
                # opt-in jax.profiler window (telemetry.profile_rounds):
                # chunk boundaries are the only safe start/stop points;
                # the chunk's round RANGE decides, so a window inside a
                # fused chunk still captures (the whole chunk)
                self.scope.profiler.observe(round_no, rounds=R)

            # host-orchestrated per-round paths (RL re-weighting, SCAFFOLD
            # controls) share the normal round bookkeeping tail
            host_round = (self._run_rl_round if self.rl is not None else
                          self._run_scaffold_round
                          if self.scaffold_store is not None else
                          self._run_ef_round
                          if self.ef_store is not None else None)
            if host_round is not None:
                with self._tspan("host_round", round=round_no):
                    host_round(round_no)
                if self.server_replay is not None:
                    # the reference runs replay after EVERY round
                    # (core/server.py:429)
                    self._run_server_replay()
                round_no += 1
                self.run_stats["secsPerRound"].append(time.time() - tic)
                self._round_housekeeping(round_no, val_freq, rec_freq)
                continue

            client_lr = self.initial_lr_client * self.lr_weight
            server_lrs = [(self.plateau.lr if self.plateau is not None
                           else self.server_lr_schedule(r))
                          for r in range(round_no, round_no + R)]
            if prefetched is not None and prefetched[0] == R:
                batches = prefetched[1]
            else:
                batches = pack_chunk(R)
            prefetched = None
            self._record_staged_bytes(batches, R)

            chunk_rng = self._next_rng()
            # flag-gated profiling (reference cProfile hooks, SURVEY §5.1)
            profile_this = (self._profile_dir is not None and
                            self._chunks_run == profile_chunk)
            if profile_this:
                jax.profiler.start_trace(self._profile_dir)
            quant_thresholds = None
            if self.quant_thresh is not None:
                # per-round annealed thresholds (core/server.py:294-298),
                # each logged at its own round like the reference
                quant_thresholds = []
                for j in range(R):
                    self.quant_thresh *= self.quant_anneal
                    quant_thresholds.append(self.quant_thresh)
                    log_metric("Quantization Thresh.", self.quant_thresh,
                               step=round_no + j)

            for ch in pending:
                # submit each pending chunk's `latest` checkpoint BEFORE
                # this dispatch donates its state buffers: the async
                # writer enqueues device-side copies that execute in
                # stream order, ahead of the donating program (only the
                # newest ring entry can still be unsaved)
                if not ch["latest_saved"]:
                    self.ckpt.save_latest(ch["state"])
                    ch["latest_saved"] = True
            if self.fleet_pager is not None:
                # fleet paging: map the chunk's cohorts onto pool slots
                # and page missing rows in (one fixed-shape donated
                # scatter, sequenced after the save_latest copies above
                # and before this dispatch) — batches gain their
                # carry_slots vectors here
                with self._tspan("fleet_page", round0=round_no,
                                 rounds=R):
                    new_sstate = self.fleet_pager.prepare_chunk(
                        batches, self.state.strategy_state)
                    if new_sstate is not self.state.strategy_state:
                        self.state = ServerState(
                            self.state.params, self.state.opt_state,
                            new_sstate, self.state.round)
            chaos_vecs = None
            if self.engine.chaos_client_faults or \
                    self.engine.chaos_corruption or \
                    self.engine.traffic_staleness:
                # deterministic per-round fault vectors (seeded on the
                # round index, resilience/chaos.py) — data operands of
                # the compiled program, so no recompile ever.  Each
                # entry carries (drop, keep_steps) and/or the
                # adversarial corruption modes and/or the arrival
                # plane's traced staleness, matching what the engine
                # compiled in (the _chaos_host arity check).
                chaos_vecs = []
                for j in range(R):
                    if self.cohort_bucketing is not None:
                        # nested per-bucket entries: each bucket grid
                        # draws its own salted sub-stream, so the
                        # schedule stays a pure function of (seed,
                        # round, bucket, slot) — serial == pipelined ==
                        # resumed, whatever the bucket layout
                        per_bucket = []
                        for bi, batch in enumerate(batches[j]):
                            entry = ()
                            if self.engine.chaos_client_faults:
                                entry += self.chaos.client_faults(
                                    round_no + j, batch.sample_mask,
                                    salt=bi + 1)
                            if self.engine.chaos_corruption:
                                entry += (self.chaos.corrupt_modes(
                                    round_no + j,
                                    batch.sample_mask.shape[0],
                                    salt=bi + 1),)
                            if self.engine.traffic_staleness:
                                # staleness keys on CLIENT id, not the
                                # bucket slot: the fire's lookup table
                                # realigns to however the packer split
                                # the cohort (padding slots map to 0)
                                entry += (self.traffic.staleness_vector(
                                    round_no + j, batch.client_ids),)
                            per_bucket.append(entry)
                        chaos_vecs.append(per_bucket)
                        continue
                    entry = ()
                    if self.engine.chaos_client_faults:
                        entry += self.chaos.client_faults(
                            round_no + j, batches[j].sample_mask)
                    if self.engine.chaos_corruption:
                        entry += (self.chaos.corrupt_modes(
                            round_no + j,
                            batches[j].sample_mask.shape[0]),)
                    if self.engine.traffic_staleness:
                        entry += (self.traffic.staleness_vector(
                            round_no + j, batches[j].client_ids),)
                    chaos_vecs.append(entry)
            # the device window span opens at dispatch and is ended by
            # whoever drains this chunk — the explicit begin/end API
            # exists exactly for this overlap (round k's window stays
            # open while the host packs/dispatches k+1)
            device_span = (self.scope.begin("round_device",
                                            round0=round_no, rounds=R)
                           if self.scope is not None else None)
            with self._tspan("dispatch", round0=round_no, rounds=R):
                if self.cohort_bucketing is not None:
                    self.state, packed = \
                        self.engine.dispatch_bucketed_rounds(
                            self.state, batches, [client_lr] * R,
                            server_lrs, chunk_rng,
                            leakage_threshold=self.max_allowed_leakage,
                            quant_thresholds=quant_thresholds,
                            chaos_vecs=chaos_vecs)
                else:
                    self.state, packed = self.engine.dispatch_rounds(
                        self.state, batches, [client_lr] * R, server_lrs,
                        chunk_rng,
                        leakage_threshold=self.max_allowed_leakage,
                        quant_thresholds=quant_thresholds,
                        chaos_vecs=chaos_vecs)
            chunk = {
                "span": device_span,
                "round0": round_no, "R": R, "state": self.state,
                "stats": packed, "batches": batches,
                "client_lr": client_lr, "server_lrs": server_lrs,
                "tic": tic, "latest_saved": False,
                # resume anchor: with lookahead packing (pipeline /
                # prefetch) the NEXT chunk's sampling happens before this
                # chunk's housekeeping, so the rng state belonging to
                # this chunk's checkpoint must be captured NOW; the plain
                # serial loop snapshots at housekeeping time instead
                # (after any server-replay randomness for these rounds)
                "rng_snapshot": (self._rng_snapshot()
                                 if (pipelined or prefetch_ok) else None),
                # adaptive-DP observability: stash a device-side copy of
                # the post-chunk clip NOW — the next dispatch donates the
                # strategy_state buffers this scalar lives in
                "dp_clip": (jnp.copy(self.state.strategy_state["dp_clip"])
                            if isinstance(self.state.strategy_state, dict)
                            and "dp_clip" in self.state.strategy_state
                            else None),
                # device-truth snapshot: which compiled entry point this
                # chunk dispatched through and what it costs (compile-
                # time facts; the drain pairs them with the measured
                # wall clock for the live MFU).  Snapshotted NOW — by
                # drain time, a newer pipelined dispatch may have
                # overwritten last_dispatch.
                "xla_dispatch": (dict(self.engine.xla.last_dispatch)
                                 if self.engine.xla is not None and
                                 self.engine.xla.last_dispatch is not None
                                 else None),
            }
            if self.fleet_pager is not None:
                # dispatch the writeback gather NOW (async, reads this
                # chunk's output tables before any later program donates
                # them — the dp_clip stash discipline); the drain
                # completes it with one explicit fetch
                chunk["fleet_wb"] = self.fleet_pager.queue_writeback(
                    self.state.strategy_state, round_no=round_no + R)
            # dispatch is async: pack the next chunk NOW, while the device
            # executes this one (reading the stats below is what blocks)
            if lookahead_pack and round_no + R < max_iteration:
                next_R = chunk_R(round_no + R)
                prefetched = (next_R, pack_chunk(next_R))
                if fleet_prefetch:
                    # hand the packed cohort to the fleet-prefetch
                    # worker: missing carry rows stage off-thread while
                    # the device executes, so the next prepare_chunk's
                    # page-in assembly is a staging-buffer copy
                    self.fleet_pager.prefetch_chunk(prefetched[1])
            if profile_this:
                jax.block_until_ready(self.state.params)
                jax.profiler.stop_trace()
                print_rank(f"wrote profiler trace to {self._profile_dir}")
            self._chunks_run += 1
            round_no += R

            while len(pending) >= self.pipeline_depth and pending:
                # ring full: drain the OLDEST chunk's host tail while the
                # device executes the newer ones (incl. the chunk just
                # dispatched) — the pipeline.  Depth 1 reproduces the
                # original one-deep behavior exactly.
                self._drain_chunk(pending.popleft(), val_freq, rec_freq)
                self.pipelined_chunks += 1
            # the tail at an eval/housekeeping boundary can change LRs,
            # params (fall-back), and sampling-relevant state for the
            # NEXT round, so the whole ring must drain before dispatching
            # past it; the final chunk always drains here too
            boundary = (round_no >= max_iteration or
                        round_no % val_freq == 0 or
                        (round_no % rec_freq == 0 and
                         self.test_dataset is not None))
            if pipelined and not boundary:
                pending.append(chunk)
            else:
                while pending:
                    self._drain_chunk(pending.popleft(), val_freq,
                                      rec_freq)
                    self.pipelined_chunks += 1
                self._drain_chunk(chunk, val_freq, rec_freq)
        while pending:
            # preemption landed with chunks in flight: the device work is
            # already done, so drain the ring in dispatch order — each
            # chunk's housekeeping writes the per-round `latest`
            # checkpoint, making those rounds part of the resume anchor
            # instead of lost work.  (Nothing speculative beyond the ring
            # is ever dispatched.)  The drain window is a first-class
            # span: checkpoint stalls inside a preemption grace period
            # are exactly what a trace reader needs to see.
            ch = pending.popleft()
            with self._tspan("preempt_drain", round0=ch["round0"],
                             rounds=ch["R"]):
                self._drain_chunk(ch, val_freq, rec_freq)
            self.pipelined_chunks += 1
        self.ckpt.wait()  # async checkpoint saves must be durable on return
        if self.preemption.requested and round_no < max_iteration:
            # resumable exit: every completed round is checkpointed and
            # durable; status_log carries the rng anchors written by the
            # last housekeeping.  e2e_trainer turns this flag into
            # os.EX_TEMPFAIL so schedulers re-queue the job.
            self.preempted = True
            # covers a signal that landed after the loop's last poll
            # (e.g. during the final drain): idempotent no-op otherwise
            self.preemption.flush_now()
            self.ckpt.update_status(
                {"preempted": self.preemption.reason or "requested"})
            emit_event(self.scope, "preempted_exit", round=round_no,
                       reason=self.preemption.reason or "requested")
            print_rank(
                f"preempted at round {round_no}/{max_iteration} "
                f"({self.preemption.reason}); checkpoint durable — resume "
                "with server_config.resume_from_checkpoint: true",
                loglevel=logging.WARNING)
        elif "preempted" in self.ckpt.read_status():
            # a resumed run that COMPLETED: clear the stale marker so the
            # final status log doesn't read as an interrupted run
            self.ckpt.update_status({"preempted": None})
        self._log_timing()
        flush_metrics()
        if self.scope is not None:
            # close any open profiler window and make trace.json
            # complete/loadable; the tracer stays open so a later
            # train() on the same server appends to the same trace
            self.scope.profiler.finish()
            self.scope.flush()
        return self.state

    # ------------------------------------------------------------------
    def _pipeline_ok(self) -> bool:
        """Whether the overlapped host/device loop may run: everything the
        host tail feeds back into the NEXT dispatch (RL rewards, SCAFFOLD/
        EF stores, replay training, the adaptive leakage threshold,
        personalization's model-dependent sampling) forces serial."""
        return self._pipeline_capable and self.rl is None and \
            self.scaffold_store is None and self.ef_store is None and \
            self.server_replay is None and self.adaptive_leakage is None

    # ------------------------------------------------------------------
    def _drain_chunk(self, chunk: Dict[str, Any], val_freq: int,
                     rec_freq: int) -> None:
        """Consume one dispatched chunk's results: fetch the packed stats
        (the honest end-of-chunk fence — ONE transfer per dtype group),
        emit the per-round metrics, process privacy stats, dump norms, and
        run the round housekeeping.  In the pipelined loop this runs while
        the device executes the NEXT chunk; in serial mode it runs
        immediately after dispatch (identical side-effect order either
        way, which the pipeline equivalence tests pin)."""
        R = chunk["R"]
        round0 = chunk["round0"]
        with self._tspan("stats_fetch", round0=round0, rounds=R):
            stats = chunk["stats"].fetch()
        if self.scope is not None:
            # the fetch is the honest end-of-chunk fence: the device
            # window that opened at dispatch closes here
            self.scope.end(chunk.get("span"))
        toc = time.time()
        # serial chunks: prep-to-fence (chunk tic follows the previous
        # fence).  Pipelined chunks: fence-to-fence — this chunk's prep
        # started BEFORE the previous chunk's fence, so tic-based timing
        # would double-count the overlapped span.
        self.run_stats["secsPerRound"].append(
            (toc - max(chunk["tic"], self._last_fence)) / R)
        self._last_fence = toc

        if self.fleet_pager is not None and chunk.get("fleet_wb"):
            # fleet paging drain half: ONE explicit fetch of this
            # chunk's updated carry rows, written through to the host
            # store; the chunk's slots unpin and become evictable.
            # Runs BEFORE the host tail so housekeeping/eval at this
            # boundary read current rows.
            with self._tspan("fleet_writeback", round0=round0,
                             rounds=R):
                self.fleet_pager.complete_writeback(chunk["fleet_wb"])

        with self._tspan("host_tail", round0=round0, rounds=R):
            self._drain_host_tail(chunk, stats, val_freq, rec_freq)
        self.run_stats["secsPerRoundHostTail"].append(
            (time.time() - toc) / R)
        if self.scope is not None:
            mfu_before = len(self.run_stats["mfuPerRound"])
            self._drain_device_truth(chunk, round0, R)
            # this chunk's live MFU, iff the device-truth tail computed
            # one just now — the rollup's per-round mfu column
            chunk_mfu = (self.run_stats["mfuPerRound"][-1]
                         if len(self.run_stats["mfuPerRound"]) > mfu_before
                         else None)
            # one host RSS reading per chunk (a /proc line — pure host
            # IO, zero device access) feeds the rss_leak detector and
            # the rollup gauge
            rss = host_rss_bytes()
            xla_snap = (self.engine.xla.snapshot()
                        if self.engine.xla is not None else
                        {"recompiles": int(self.engine.recompile_count)})
            # fleet + dataset-cache gauges: host counters the loop
            # already owns (zero device access), published per chunk
            # through the host-side bus and handed to the rollup window
            # so `scope watch`/`scope health` see paging pressure live
            fleet_gauges = {}
            if self.fleet_pager is not None:
                pd = self.fleet_pager.describe()
                for key in ("hits", "misses", "evictions", "resident"):
                    fleet_gauges[f"fleet_page_{key}"] = pd[key]
                    self.scope.devbus_host(f"fleet_page_{key}", pd[key],
                                           step=round0 + R - 1)
                # transfer-plane accounting (mesh-sharded pool): this
                # chunk's page-in/writeback bytes off the completed
                # handle, plus the cumulative per-device split and the
                # prefetch hit rate — what `scope diff/trend --gate`
                # watches for a replication regression (per-device
                # bytes snapping back to the total)
                wb = chunk.get("fleet_wb") or {}
                self.scope.devbus_host(
                    "fleet_page_in_bytes",
                    wb.get("page_in_bytes", 0), step=round0 + R - 1)
                self.scope.devbus_host(
                    "fleet_writeback_bytes",
                    wb.get("writeback_bytes", 0), step=round0 + R - 1)
                if pd["prefetch_hit_rate"] is not None:
                    # None = prefetch never engaged this run (serial /
                    # sample-hooked / prefetch-off): no coverage to
                    # report, nothing for the diff gate to read
                    self.scope.devbus_host(
                        "fleet_prefetch_hit_rate",
                        pd["prefetch_hit_rate"], step=round0 + R - 1)
                for key in ("page_in_bytes", "page_in_bytes_per_device",
                            "writeback_bytes",
                            "writeback_bytes_per_device",
                            "prefetch_hit_rate", "migrations",
                            "forced_drains"):
                    if pd[key] is not None:
                        fleet_gauges[f"fleet_{key}"] = pd[key]
            cache_stats_fn = getattr(self.train_dataset, "cache_stats",
                                     None)
            if cache_stats_fn is not None:
                cs = cache_stats_fn()
                for key in ("hits", "misses", "evictions", "resident"):
                    fleet_gauges[f"lazy_cache_{key}"] = cs[key]
                    self.scope.devbus_host(f"lazy_cache_{key}", cs[key],
                                           step=round0 + R - 1)
            mgb_util = (self.megabatch_utilization
                        if self.megabatch is not None else None)
            if mgb_util is not None:
                # live tape occupancy for `scope watch`/rollups; absent
                # (not 0.0) until a bucket actually attached a tape
                fleet_gauges["megabatch_utilization"] = mgb_util
                self.scope.devbus_host("megabatch_utilization",
                                       mgb_util, step=round0 + R - 1)
            if fleet_gauges and self.scope.rollup is not None:
                self.scope.rollup.update_gauges(fleet_gauges)
            # watchdogs run over values this tail ALREADY holds: the
            # fetched per-round losses, the wall clock, the checkpoint
            # escalator's consecutive-failure count.  A configured
            # `abort` raises WatchdogAbort out of the round loop.
            secs = self.run_stats["secsPerRound"][-1]
            for j in range(R):
                n = max(float(stats["client_count"][j]), 1.0)
                quarantine_frac = None
                if "shield_nonfinite" in stats:
                    # quarantined / live cohort (client_count is the
                    # POST-screen count, so the cohort adds them back) —
                    # the quarantine_rate detector's "a few bad clients
                    # vs the model itself diverging" signal
                    q = (float(stats["shield_nonfinite"][j]) +
                         float(stats["shield_norm_outlier"][j]))
                    quarantine_frac = q / max(
                        q + float(stats["client_count"][j]), 1.0)
                self.scope.watchdog.observe_round(
                    round0 + j,
                    train_loss=float(stats["train_loss_sum"][j]) / n,
                    round_secs=secs,
                    ckpt_failures=self.ckpt.escalator.consecutive,
                    quarantine_frac=quarantine_frac,
                    # always-on engine counter (compiled variants beyond
                    # the first per entry point) — feeds recompile_storm
                    recompiles=self.engine.recompile_count,
                    host_rss_bytes=rss)
                # endurance rollup (ISSUE 13): the same already-held
                # host values, windowed — zero new transfers
                self.scope.rollup_observe(
                    round0 + j, secs,
                    clients=float(stats["client_count"][j]),
                    mfu=chunk_mfu, rss_bytes=rss,
                    xla_snapshot=xla_snap)

    def _drain_host_tail(self, chunk: Dict[str, Any], stats,
                         val_freq: int, rec_freq: int) -> None:
        """The decode/log/housekeeping half of :meth:`_drain_chunk`
        (split out so the whole region is one ``host_tail`` span)."""
        R = chunk["R"]
        round0 = chunk["round0"]
        # per-round logging (reference core/server.py:362-395 + AzureML)
        for j in range(R):
            r = round0 + j
            n_clients = max(float(stats["client_count"][j]), 1.0)
            log_metric("Training loss",
                       float(stats["train_loss_sum"][j]) / n_clients, step=r)
            log_metric("LR for agg. opt.", chunk["server_lrs"][j], step=r)
            log_metric("Client learning rate", chunk["client_lr"], step=r)
            log_metric("Agg. grad norm",
                       float(stats["agg_grad_norm"][j]), step=r)
        if self.scope is not None:
            # bus-published device scalars: decoded from the SAME packed
            # fetch as everything above (zero extra transfers)
            self.scope.consume_devbus(stats, round0, R)
        if self.chaos is not None and "chaos_dropped" in stats:
            # injected-fault observability: counters computed inside the
            # round program, fetched through the SAME packed single
            # transfer as every other stat (no extra host syncs)
            counters = self.chaos.counters
            for j in range(R):
                r = round0 + j
                dropped = float(stats["chaos_dropped"][j])
                straggled = float(stats["chaos_straggled"][j])
                lost = float(stats["chaos_steps_lost"][j])
                counters["dropped"] += dropped
                counters["straggled"] += straggled
                counters["steps_lost"] += lost
                log_metric("Chaos dropped clients", dropped, step=r)
                log_metric("Chaos stragglers", straggled, step=r)
                log_metric("Chaos steps lost", lost, step=r)
                if dropped or straggled or lost:
                    # structured fault record (metrics stream + trace
                    # instant), not just greppable metric lines
                    emit_event(self.scope, "chaos_faults", round=r,
                               dropped=dropped, straggled=straggled,
                               steps_lost=lost)
        if self.chaos is not None and "chaos_nan_injected" in stats:
            # adversarial corruption counters (fluteshield's attack
            # half): same packed-transfer discipline as the fault
            # counters above
            counters = self.chaos.counters
            for j in range(R):
                r = round0 + j
                nans = float(stats["chaos_nan_injected"][j])
                scaled = float(stats["chaos_scaled"][j])
                flipped = float(stats["chaos_sign_flipped"][j])
                counters["nan_injected"] += nans
                counters["scaled"] += scaled
                counters["sign_flipped"] += flipped
                log_metric("Chaos NaN-injected clients", nans, step=r)
                log_metric("Chaos scaled clients", scaled, step=r)
                log_metric("Chaos sign-flipped clients", flipped, step=r)
                if nans or scaled or flipped:
                    emit_event(self.scope, "chaos_corruption", round=r,
                               nan_injected=nans, scaled=scaled,
                               sign_flipped=flipped)
        if self.traffic is not None and "traffic_stale_sum" in stats:
            # arrival-plane observability: the on-device staleness
            # histogram rides the SAME packed transfer as every other
            # stat; the schedule's host-side rollups are the replay
            # oracle these counters are cross-checked against
            # (tests/test_traffic.py)
            for j in range(R):
                r = round0 + j
                stale_sum = float(stats["traffic_stale_sum"][j])
                hist = [float(stats[f"traffic_stale_{b}"][j])
                        for b in range(STALE_HIST_BINS)]
                log_metric("Traffic staleness sum", stale_sum, step=r)
                emit_event(self.scope, "traffic_staleness", round=r,
                           stale_sum=stale_sum, hist=hist)
        if self.shield is not None and "shield_nonfinite" in stats:
            # fluteshield quarantine observability: per-cause counters
            # computed inside the round program, fetched through the
            # SAME packed single transfer as every other stat
            counters = self.shield.counters
            for j in range(R):
                r = round0 + j
                nonfinite = float(stats["shield_nonfinite"][j])
                outlier = float(stats["shield_norm_outlier"][j])
                counters["quarantined_nonfinite"] += nonfinite
                counters["quarantined_norm_outlier"] += outlier
                log_metric("Quarantined clients (non-finite)", nonfinite,
                           step=r)
                log_metric("Quarantined clients (norm outlier)", outlier,
                           step=r)
                if nonfinite or outlier:
                    emit_event(self.scope, "quarantine", round=r,
                               nonfinite=nonfinite, norm_outlier=outlier)
        if getattr(self.strategy, "wants_cohort", False) and \
                "secagg_recovered_dropout" in stats:
            # secure-agg mask-recovery observability: per-cause recovery
            # counts and the liveness-floor abort flag computed inside
            # the round program, fetched through the SAME packed single
            # transfer as every other stat
            counters = self.strategy.counters
            for j in range(R):
                r = round0 + j
                rec_drop = float(stats["secagg_recovered_dropout"][j])
                rec_quar = float(stats["secagg_recovered_quarantine"][j])
                counters["recovered_dropout"] += rec_drop
                counters["recovered_quarantine"] += rec_quar
                log_metric("SecAgg recovered (dropout)", rec_drop, step=r)
                log_metric("SecAgg recovered (quarantine)", rec_quar,
                           step=r)
                if rec_drop or rec_quar:
                    emit_event(self.scope, "secagg_recovered", round=r,
                               dropout=rec_drop, quarantine=rec_quar)
                if "secagg_abort" in stats:
                    aborted = float(stats["secagg_abort"][j])
                    if aborted:
                        counters["aborted_rounds"] += aborted
                        log_metric("SecAgg aborted round", aborted,
                                   step=r)
                        emit_event(self.scope, "secagg_abort", round=r,
                                   aborted=aborted)
        self._process_privacy_stats(
            stats, round0,
            client_mask=self._chunk_client_masks(chunk["batches"]))
        if chunk["dp_clip"] is not None:
            # adaptive DP clipping observability (arXiv:1905.03871); the
            # post-chunk value is the clip the NEXT round applies, so it
            # logs at that round's step.  Explicit fetch: float() on the
            # device scalar was an implicit sync (strict transfer mode)
            log_metric("DP clip norm",
                       float(jax.device_get(chunk["dp_clip"])),
                       step=round0 + R)
        if self.engine.dump_norm_stats and "norm" in stats:
            self._dump_norm_stats(stats, chunk["batches"])
        if self.server_replay is not None:
            self._run_server_replay()
        self._round_housekeeping(round0 + R, val_freq, rec_freq,
                                 skip_latest=chunk["latest_saved"],
                                 rng_snapshot=chunk.get("rng_snapshot"))

    # ------------------------------------------------------------------
    # flutescope device-truth (telemetry/xla.py): the host-tail half.
    # Compile-time facts (FLOPs, HBM bytes, recompile findings) pair
    # with the wall clocks the loop ALREADY measures — no device access,
    # no new transfers, clean under strict mode by construction.
    # ------------------------------------------------------------------
    def _drain_xla_events(self) -> None:
        """Emit the introspector's buffered compile/recompile events as
        structured records (metrics stream + trace instants), plus the
        attention dispatch gate's fallback records
        (ops/pallas_attention.py — buffered at plan time, host-side)."""
        if self.scope is None:
            return
        from ..ops.pallas_attention import drain_attention_events
        for ev in drain_attention_events():
            self.scope.event(ev.pop("kind"), **ev)
        # megabatch dispatch-gate fallbacks (engine-buffered: the
        # server's analytic slots gate and the aot_cost shootout both
        # push here) — same loud-fallback surface as the attention gate
        for ev in self.engine.drain_megabatch_events():
            self.scope.event(ev.pop("kind"), **ev)
        reg = self.engine.xla
        if reg is None:
            return
        for ev in reg.drain_events():
            self.scope.event(ev.pop("kind"), **ev)

    def _drain_device_truth(self, chunk: Dict[str, Any], round0: int,
                            R: int) -> None:
        """Per-chunk device-truth tail: drain compile events, then the
        live MFU — the chunk's compiled FLOPs (snapshotted at dispatch)
        over the measured per-round wall clock and the chip's peak —
        and the program's HBM footprint, published through the host-side
        bus (metric lines + trace counters; zero device reads)."""
        self._drain_xla_events()
        disp = chunk.get("xla_dispatch")
        if not disp or not disp.get("flops") or self._chip is None:
            return
        from ..telemetry.xla import mfu as _mfu
        flops_per_round = float(disp["flops"]) / max(
            int(disp.get("rounds") or R), 1)
        secs = self.run_stats["secsPerRound"][-1]
        value = _mfu(flops_per_round, secs, peak_flops=self._chip[1])
        if value is not None:
            self.run_stats["mfuPerRound"].append(value)
            self.scope.devbus_host("mfu", value, step=round0 + R - 1)
        hbm = disp.get("hbm_bytes")
        if hbm:
            self.scope.devbus_host("hbm_program_gb", hbm / 2 ** 30,
                                   step=round0 + R - 1)

    def build_scorecard(self) -> Dict[str, Any]:
        """The run's compact regression surface
        (``telemetry/scorecard.json``): the metrics ``tools/scope diff``
        thresholds and the endurance harness gates on.  Every value is
        something the run already measured — wall clocks, the overlap
        geometry from the flushed trace, the device-truth layer's
        compile-time numbers, watchdog findings."""
        rs = self.run_stats

        def p50(values):
            return (round(float(np.percentile(values, 50)), 6)
                    if values else None)

        card: Dict[str, Any] = {
            "rounds": int(self.state.round),
            "pipeline_depth": int(self.pipeline_depth),
            "pipelined_chunks": int(self.pipelined_chunks),
            "round_secs_p50": p50(rs["secsPerRound"]),
            "host_tail_secs_p50": p50(rs["secsPerRoundHostTail"]),
            "staged_bytes_per_round_p50": p50(
                rs["hostToDeviceBytesPerRound"]),
            # run-total real samples / padded grid slots (slots- i.e.
            # FLOPs-weighted, NOT a per-chunk mean — cheap chunks must
            # not mask waste on expensive ones)
            "padding_efficiency": (
                round(self.padding_efficiency, 6)
                if self.padding_efficiency is not None else None),
            "mfu_p50": p50(rs["mfuPerRound"]),
            "puts_per_dispatch": int(self.engine.last_dispatch_puts),
            "compiles": len(self.engine.compile_log),
            "recompiles": int(self.engine.recompile_count),
        }
        fires: Dict[str, int] = {}
        if self.scope is not None:
            for finding in self.scope.watchdog.findings:
                kind = str(finding.get("kind", "?"))
                fires[kind] = fires.get(kind, 0) + 1
        card["watchdog_fires"] = fires
        if self.scope is not None and self.scope.tracer is not None:
            # the Tracer's 1M-event cap used to drop silently past the
            # in-trace flag; endurance gates need the drop COUNT on the
            # regression surface (ISSUE 13 satellite)
            card["trace_events_dropped"] = int(self.scope.tracer.dropped)
        if self.scope is not None and self.scope.rollup is not None:
            card["rollup_windows"] = int(
                self.scope.rollup.windows_flushed)
            # the degradation table's telemetry ledger: windows lost to
            # writer exhaustion (always present when rollups are on —
            # 0 is the healthy reading the drill gates against)
            card["rollup_windows_dropped"] = int(
                self.scope.rollup.windows_dropped)
        if self.chaos is not None and self.chaos.infra is not None:
            # seeded infra-fault ledger (chaos.infra): per-surface
            # injected-fault counts — a drill run is impossible to
            # confuse with a clean one on the regression surface
            card["infra_faults"] = {
                k: float(v)
                for k, v in sorted(self.chaos.infra.counters.items())}
        if self.fleet_pager is not None:
            # paging pressure joins the regression surface: a hit-rate
            # collapse or an eviction storm is a fleet-sizing regression
            # `scope diff`/`scope health` should see
            card["fleet"] = self.fleet_pager.describe()
            # flat copies for the `scope diff --gate` rules (DIFF_RULES
            # reads top-level scorecard keys): per-device transfer
            # bytes are the replication-regression tripwire — a
            # replicated pool multiplies them by mesh_size
            card["fleet_page_in_bytes_per_device"] = \
                card["fleet"]["page_in_bytes_per_device"]
            card["fleet_writeback_bytes_per_device"] = \
                card["fleet"]["writeback_bytes_per_device"]
            if card["fleet"]["prefetch_hit_rate"] is not None:
                # absent (not 0.0) when prefetch never engaged, so the
                # diff gate's lower_abs rule skips instead of flagging
                # a non-prefetching arm as a coverage regression
                card["fleet_prefetch_hit_rate"] = \
                    card["fleet"]["prefetch_hit_rate"]
        cache_stats_fn = getattr(self.train_dataset, "cache_stats", None)
        if cache_stats_fn is not None:
            card["lazy_cache"] = cache_stats_fn()
        if self.cohort_bucketing is not None:
            card["cohort_bucketing"] = {
                "boundaries": list(self.cohort_bucketing["boundaries"]),
                "max_buckets": int(self.cohort_bucketing["max_buckets"]),
                # compiled-grid closure: distinct (K_b, S_b) collect
                # shapes this run compiled (gated <= max_buckets in the
                # bench A/B; churn past warmup trips the sentinel)
                "bucket_grid_variants":
                    len(self.engine.bucket_shapes_seen),
            }
        if self.megabatch is not None:
            util = self.megabatch_utilization
            card["megabatch"] = {
                "lanes": [int(l) for l in self.megabatch["lanes"]],
                "utilization": (round(util, 6)
                                if util is not None else None),
                # dispatch gate's chosen arm per compiled bucket shape
                # ("mega" | "vmap") — the regression surface for a
                # silently-fallen-back bucket
                "gate_arms": {f"K{k}_S{s}": arm for (k, s), arm in
                              sorted(self.engine._mega_gate.items())},
            }
            # flat copy for the `scope diff --gate` lower_frac rule
            card["megabatch_utilization"] = \
                card["megabatch"]["utilization"]
        if self.traffic is not None:
            # arrival-plane rollups (traffic/schedule.py): the trace
            # identity plus the host replay oracle's counters — enough
            # to make a traffic run impossible to confuse with a
            # boundary-sampled baseline in `scope diff`
            card["traffic"] = {
                **self.traffic.describe(),
                "arrival_rate": round(self.traffic.arrival_rate(), 6),
                "mean_buffer_occupancy": round(
                    self.traffic.mean_buffer_occupancy(), 6),
                "stale_hist": [int(c) for c in self.traffic.stale_hist],
                "counters": {k: float(v) for k, v in
                             self.traffic.counters.items()},
                "target_accuracy": self.target_accuracy,
                "rounds_to_target_accuracy":
                    self.rounds_to_target_accuracy,
            }
        reg = self.engine.xla
        if reg is not None:
            card["entry_points"] = reg.summary()
            card["hbm_peak_bytes"] = reg.hbm_peak_bytes()
            if self._chip is not None:
                card["chip"] = {"kind": self._chip[0],
                                "peak_flops": self._chip[1]}
        # overlap geometry from the flushed trace — via the ONE reader
        # (scope_cli.summarize), so the scorecard and `tools/scope`
        # can never disagree about the efficiency number
        try:
            from ..telemetry.scope_cli import summarize
            overlap = summarize(self.ckpt.model_dir).get("overlap") or {}
            card["overlap_efficiency_pct"] = overlap.get("efficiency_pct")
            if "by_depth" in overlap:
                card["host_tail_by_depth_s"] = overlap["by_depth"]
            if "max_rounds_in_flight" in overlap:
                card["max_rounds_in_flight"] = \
                    overlap["max_rounds_in_flight"]
        except Exception:
            card["overlap_efficiency_pct"] = None
        return card

    # ------------------------------------------------------------------
    def _record_staged_bytes(self, batches: list, rounds: int) -> None:
        """Host->device payload per round (the design's whole communication
        story: pool mode ships int32 indices, host packing ships feature
        bytes) — the TPU-native counterpart of the reference's per-client
        ``communicationCosts`` timing (``core/server.py:317,353``);
        reported by ``_log_timing``.  Called from the fused path AND the
        host-orchestrated (RL/SCAFFOLD) rounds, which also ship a packed
        batch.  Bucketed chunks pass the nested per-round bucket lists;
        the bytes sum over every grid either way."""
        flat = [b for entry in batches
                for b in (entry if isinstance(entry, list) else [entry])]
        chunk_bytes = sum(
            sum(a.nbytes for a in
                (getattr(b, "arrays", None) or
                 {"__idx__": b.indices}).values())
            + b.sample_mask.nbytes for b in flat)
        self.run_stats["hostToDeviceBytesPerRound"].append(
            chunk_bytes / max(rounds, 1))

    # ------------------------------------------------------------------
    def _maybe_length_bucket(self, batches: list) -> None:
        """Crop the chunk's token grids to their real-length bucket (see
        ``data.batching.seq_length_bucket``); logs the padding-efficiency
        ratio like the reference's DynamicBatchSampler meter."""
        keys = getattr(self.task, "seq_pad_keys", ())
        if not self.length_bucketing or not keys:
            return
        from ..data.batching import seq_length_bucket
        stats = seq_length_bucket(batches, keys)
        if stats is not None and stats["cropped"]:
            self._length_bucket_stats = stats
            print_rank(
                f"length bucket L={stats['bucket']}/{stats['full_len']} "
                f"pad-eff {stats['tokens_real'] / max(stats['tokens_grid_after'], 1):.3f}"
                f" (was {stats['tokens_real'] / max(stats['tokens_grid_before'], 1):.3f})",
                loglevel=logging.DEBUG)

    # ------------------------------------------------------------------
    def _pack_bucketed_round(self, sampled: list) -> list:
        """One round's cohort as per-bucket compact grids
        (``server_config.cohort_bucketing``): deterministic assignment
        of each sampled client to the smallest step bucket covering its
        need, one ``[K_b, S_b, B, ...]`` grid per occupied bucket with
        ``K_b`` pow2-quantized (then mesh-padded) so the compiled grid
        variant set stays small and closed."""
        from ..data.batching import assign_step_buckets
        needs = [int(self._step_needs[i]) for i in sampled]
        caps = self.cohort_bucketing["capacities"]
        bounds = self.cohort_bucketing["boundaries"]
        assignment = assign_step_buckets(needs, bounds, capacities=caps)
        # pre-draw every sampled client's shuffle permutation in COHORT
        # order — the exact rng calls the monolithic pack would make —
        # so bucketing changes only grid SHAPES, never which samples a
        # client trains on or any later round's sampling stream
        orders = {int(ci): self._np_rng.permutation(
                      int(self.train_dataset.num_samples[ci]))
                  for ci in sampled}
        out = []
        for bi, ((s_b, positions), cap) in enumerate(
                zip(assignment.items(), caps)):
            ids = [sampled[p] for p in positions]
            cap = int(cap)
            # TOP-bucket overflow (sampling variance beyond the slack)
            # splits into EXTRA GRIDS OF THE SAME COMPILED SHAPE — the
            # collect-variant set stays exactly one program per bucket,
            # deterministically; only the finalize (one more partial in
            # its signature) retraces, once per new grid count
            groups = ([ids] if len(ids) <= cap else
                      [ids[i:i + cap] for i in range(0, len(ids), cap)])
            tapes = None
            if self.megabatch is not None and ids:
                from ..data.batching import plan_megabatch
                L = int(self.megabatch["lanes"][bi])
                E = int(self.megabatch["epochs"])
                plan = plan_megabatch(
                    [needs[p] for p in positions], E, L, int(s_b),
                    self.mesh.shape[CLIENTS_AXIS], cap)
                # analytic slots gate: per lane-scan step the tape
                # trains L lanes for depth=E*S steps vs the per-client
                # grid's cap rows for S steps x E epochs — compute
                # ratio reduces to groups*L vs groups*cap.  The tape
                # must win by min_gain or the bucket falls back LOUDLY
                # to the vmap arm (buffered megabatch_fallback event,
                # the flash-vs-dense discipline)
                gain = 1.0 + float(self.megabatch["min_gain"])
                if len(plan) * L * gain <= len(groups) * cap:
                    # planned row order (shard-local blocks, -1 holes)
                    # replaces the plain cohort split; the hole-aware
                    # packers keep grid rows aligned to the tape's
                    # segment ids
                    groups = [[ids[j] if j >= 0 else -1 for j in rows]
                              for rows, _ in plan]
                    tapes = [t for _, t in plan]
                else:
                    self.engine.push_megabatch_event({
                        "kind": "megabatch_fallback", "reason": "slots",
                        "bucket_steps": int(s_b), "clients": len(ids),
                        "lanes": L, "tape_groups": len(plan),
                        "grid_groups": len(groups)})
            for gi, g in enumerate(groups):
                if self._pool_offsets is not None:
                    from ..data.batching import pack_round_indices
                    b = pack_round_indices(
                        self.train_dataset, self._pool_offsets, g,
                        self.batch_size, s_b, rng=self._np_rng,
                        pad_clients_to=cap, orders=orders,
                        desired_max_samples=self.desired_max_samples)
                else:
                    b = pack_round_batches(
                        self.train_dataset, g, self.batch_size, s_b,
                        rng=self._np_rng, pad_clients_to=cap,
                        orders=orders,
                        desired_max_samples=self.desired_max_samples)
                if tapes is not None:
                    t = tapes[gi]
                    b.mega = t
                    self._mega_slots += float(
                        t.lanes * t.depth * self.batch_size)
                    self._mega_real += float(
                        t.entries * self.batch_size)
                out.append(b)
        return out

    def _record_padding_efficiency(self, batches_flat: list) -> None:
        """Real samples / padded grid slots of one packed chunk — the
        meter the cohort-bucketing win is gated on (scorecard +
        ``tools/scope diff`` + bench A/B).  The per-chunk ratio joins
        ``run_stats`` for observability; the GATED number is the
        run-total ratio (:attr:`padding_efficiency`) — slots-weighted,
        i.e. FLOPs-weighted, so cheap small-cohort chunks cannot mask
        waste on the expensive ones.

        Megabatch grids count their TAPE slots (``lanes * depth * B``,
        per-epoch-normalized to match the grid convention) instead of
        the ``K*S*B`` grid the tape re-reads — the lane scan's compute
        is the tape, so the meter keeps meaning "real samples / sample
        slots the round actually paid for"."""
        from ..data.batching import grid_slots, padding_efficiency
        if self.megabatch is None:
            self.run_stats["paddingEfficiency"].append(
                padding_efficiency(batches_flat))
            self._pad_slots += grid_slots(batches_flat)
            self._pad_real += float(sum(np.sum(b.num_samples)
                                        for b in batches_flat))
            return
        E = max(int(self.megabatch["epochs"]), 1)
        slots = 0.0
        for b in batches_flat:
            t = getattr(b, "mega", None)
            if t is None:
                slots += grid_slots([b])
            else:
                slots += (float(t.lanes * t.depth)
                          * int(b.sample_mask.shape[2]) / E)
        real = float(sum(np.sum(b.num_samples) for b in batches_flat))
        self.run_stats["paddingEfficiency"].append(
            real / max(slots, 1.0))
        self._pad_slots += slots
        self._pad_real += real

    @property
    def padding_efficiency(self) -> Optional[float]:
        """Run-total real samples / padded grid slots (1.0 = zero
        padding waste); None before any chunk packed."""
        if not self._pad_slots:
            return None
        return self._pad_real / self._pad_slots

    @property
    def megabatch_utilization(self) -> Optional[float]:
        """Run-total real tape entries / super-batch slots (1.0 = every
        lane-scan step trains a real client batch; idle tape padding is
        the complement).  None before any bucket attached a tape —
        distinct from 0.0, so diff gates skip non-megabatch arms."""
        if not self._mega_slots:
            return None
        return self._mega_real / self._mega_slots

    # ------------------------------------------------------------------
    def _chunk_steps(self, chunk_samples: list) -> int:
        """Step grid for one fused chunk: the dataset-wide ``max_steps``
        worst case, or (``step_bucketing``, default) the chunk's own max
        rounded up to a power of two — bounded retraces, identical math."""
        if not self.step_bucketing:
            return self.max_steps
        need = max(steps_for(self.train_dataset.num_samples[i],
                             self.batch_size, self.desired_max_samples)
                   for sampled in chunk_samples for i in sampled)
        pow2 = 1 << max(need - 1, 0).bit_length()
        return min(self.max_steps, pow2)

    def _run_server_replay(self) -> None:
        """Replay training on server-held data after aggregation
        (reference ``core/server.py:429-442``)."""
        if not hasattr(self, "_replay_fn"):
            from ..data.dataset import ArraysDataset
            from .client_update import ClientHParams, build_client_update
            replay = self.server_replay
            updatable = replay.get("updatable_names")
            # empty list means "freeze everything", which is distinct from
            # None ("no allowlist"): use an explicit None check
            hp = ClientHParams(
                num_epochs=replay["iterations"],
                updatable_layers=(tuple(updatable) if updatable is not None
                                  else None))
            self._replay_update = build_client_update(
                self.task, replay["opt_cfg"], hp)
            merged = ArraysDataset.concat_users(replay["dataset"])
            n = len(next(iter(merged.values())))
            bs = int(self.config.server_config.data_config.train.get(
                "batch_size", self.batch_size))
            # geometry is static (same jitted program every round); the
            # *contents* are re-packed per round below — the reference
            # re-iterates a shuffling DataLoader each round
            # (core/server.py:429-442), so sample order must not freeze
            self._replay_pack = (ArraysDataset(["server"], [merged]),
                                 bs, steps_for(n, bs))
            lr = float(replay["opt_cfg"].get("lr", 0.01))

            def fn(params, arrays, mask, rng):
                pg, tl, ns, _ = self._replay_update(
                    params, arrays, mask, jnp.asarray(lr, jnp.float32), rng)
                return jax.tree.map(lambda w, g: w - g, params, pg), tl
            self._replay_fn = jax.jit(fn)
        rng = self._next_rng()
        one, bs, steps = self._replay_pack
        batch = pack_round_batches(one, [0], bs, steps, rng=self._np_rng)
        arrays = {k: v[0] for k, v in batch.arrays.items()}
        mask = batch.sample_mask[0]
        new_params, tl = self._replay_fn(self.state.params, arrays, mask, rng)
        self.state = ServerState(new_params, self.state.opt_state,
                                 self.state.strategy_state, self.state.round)
        # explicit fetch: float(tl) was an implicit sync on the in-flight
        # replay program (host-sync lint + strict transfer mode)
        print_rank(f"server replay loss {float(jax.device_get(tl)):.4f}")

    def _dump_norm_stats(self, stats, batches) -> None:
        """Append per-round client grad norms + cosines-vs-aggregate
        (reference ``norm_stats.txt``/``cosines.txt``,
        ``core/server.py:392-395``, ``core/strategies/fedavg.py:149-152``)."""
        import json as _json
        norms = np.asarray(stats["norm"])      # [R, K]
        cosines = np.asarray(stats["cosine"])  # [R, K]
        masks = np.stack([b.client_mask for b in batches]) > 0
        with open(os.path.join(self.ckpt.model_dir, "norm_stats.txt"),
                  "a", encoding="utf-8") as fh:
            for r in range(norms.shape[0]):
                fh.write(_json.dumps(norms[r][masks[r]].tolist()) + "\n")
        with open(os.path.join(self.ckpt.model_dir, "cosines.txt"),
                  "a", encoding="utf-8") as fh:
            for r in range(cosines.shape[0]):
                fh.write(_json.dumps(cosines[r][masks[r]].tolist()) + "\n")

    # ------------------------------------------------------------------
    def _round_housekeeping(self, round_no: int, val_freq: int,
                            rec_freq: int,
                            skip_latest: bool = False,
                            rng_snapshot: Optional[Dict[str, Any]] = None
                            ) -> None:
        """Eval cadence, LR plateau decay, fallback, checkpoint, status log
        (reference ``core/server.py:448-490``).  ``skip_latest``: the
        pipelined loop already submitted this round's ``latest`` save
        before the next dispatch donated the state buffers.
        ``rng_snapshot``: the resume anchor captured at dispatch time when
        lookahead packing overlaps (see ``_rng_snapshot``); None means
        "capture now" (plain serial loop, host-orchestrated rounds)."""
        with self._tspan("housekeeping", round=round_no):
            self._round_housekeeping_inner(round_no, val_freq, rec_freq,
                                           skip_latest, rng_snapshot)

    def _round_housekeeping_inner(self, round_no: int, val_freq: int,
                                  rec_freq: int, skip_latest: bool,
                                  rng_snapshot: Optional[Dict[str, Any]]
                                  ) -> None:
        housekeeping_tic = time.time()
        improved = False
        if round_no % val_freq == 0:
            improved = self._maybe_eval("val", round_no)
            # client-LR decay on val plateau (core/server.py:464-469)
            if not improved and self.lr_decay_factor != 1.0:
                self.lr_weight *= float(self.lr_decay_factor)
                print_rank(f"decayed client lr weight to {self.lr_weight}")
            if self.plateau is not None and "loss" in self._last_val and \
                    np.isfinite(self._last_val["loss"].value):
                # non-finite val loss: skip the plateau step rather than
                # corrupt its best/bad_rounds history (NaN compares
                # False against everything — the tracker would count a
                # permanent plateau and decay the LR to the floor)
                self.plateau.step(self._last_val["loss"].value)
            if self.fall_back_to_best and not improved:
                self._fall_back()
        if round_no % rec_freq == 0 and self.test_dataset is not None:
            self._maybe_eval("test", round_no)

        status_update = {
            "i": round_no,
            "weight": self.lr_weight,
            # rng resume anchors: numpy bit-generator state + device-key
            # use counter, captured at the point all randomness for
            # rounds <= round_no (and none beyond) has been drawn
            **(rng_snapshot if rng_snapshot is not None
               else self._rng_snapshot()),
            **{f"best_val_{k}": m.value for k, m in self.best_val.items()},
        }
        if self.best_val:
            status_update["best_val_hib"] = {
                k: bool(m.higher_is_better)
                for k, m in self.best_val.items()}
        if self.plateau is not None:
            status_update["plateau"] = {
                "lr": self.plateau.lr, "best": self.plateau.best,
                "bad_rounds": self.plateau.bad_rounds}
        # the status write leads the round's durable sequence (status ->
        # rows/marker -> checkpoint), and the ring keeps one snapshot
        # per recent round: whatever slot a crash leaves loadable, the
        # anchors for exactly that round are already durable
        # (flutearmor crash-point contract — _paired_status)
        self._status_ring.append([int(round_no), dict(status_update)])
        del self._status_ring[:-16]
        status_update["status_ring"] = self._status_ring
        self.ckpt.update_status(status_update)

        with self._tspan("ckpt_submit", round=round_no):
            if not skip_latest:
                self.ckpt.save_latest(self.state)
            self.ckpt.backup(self.state, round_no,
                             best_names=tuple(self.best_val))
        if self.scaffold_store is not None:
            # commit the control-round marker only once the paired model
            # checkpoint is DURABLE (async orbax saves land out of band):
            # clean restarts then keep accumulated controls; a crash inside
            # the round window leaves the -1 sentinel and resets safely.
            # The wait() (a real stall under orbax OR checkpoint_async —
            # and load-bearing in both) deliberately serializes the async
            # save for SCAFFOLD rounds: committing the marker lazily
            # against the previous durable slot would let the control files
            # run one round ahead of the marker — the silent controls/params
            # mismatch this marker exists to prevent — and scaffold rounds
            # are host-transfer-bound anyway
            self.ckpt.wait()
            if self.scaffold_device is not None:
                # write the dirty HBM rows through to the durable store
                # before the marker claims they exist.  Flush cadence
                # (scaffold_flush_freq, default 1) bounds the per-round
                # [D, n_params] fetch: at freq > 1 the rounds in between
                # fetch only logging scalars and the marker stays at the
                # -1 sentinel — so a stop inside the window makes resume
                # reset ALL controls (marker mismatch semantics), not just
                # the unflushed tail.  That is the transfer-bound
                # deployment's tradeoff (controls are estimates and
                # re-warm), not the default.
                flush_freq = int(self.config.server_config.get(
                    "scaffold_flush_freq", 1) or 1)
                # the iteration count train() stashed at entry — a second
                # sc.get() here could desync and either flush every round
                # or never fire the final-round flush
                final = round_no >= self._max_iteration
                if flush_freq <= 1 or round_no % flush_freq == 0 or final:
                    self.scaffold_device.flush()
                    self.scaffold_store.set_round(int(self.state.round))
            else:
                self.scaffold_store.set_round(int(self.state.round))
        if self.ef_store is not None:
            # same durable-pairing rule as the SCAFFOLD marker above
            self.ckpt.wait()
            if self.ef_device is not None:
                # mirror the scaffold_flush_freq tradeoff: between flushes
                # the marker stays at the -1 sentinel, so a stop inside
                # the window resets ALL residuals on resume (graceful —
                # EF degrades to memoryless for one participation)
                flush_freq = int(self.config.server_config.get(
                    "ef_flush_freq", 1) or 1)
                final = round_no >= self._max_iteration
                if flush_freq <= 1 or round_no % flush_freq == 0 or final:
                    self.ef_device.flush()
                    self.ef_store.set_round(int(self.state.round))
            else:
                self.ef_store.set_round(int(self.state.round))
        if self.fleet_pager is not None:
            # fleet paged-carry durability: the host store already holds
            # every drained row (writeback-on-drain); spill the dirty
            # ones to disk and commit the round marker only once the
            # paired model checkpoint is durable — the ControlStore
            # pairing rule.  Unlike the control stores, a hard stop
            # inside this window stays bit-identically resumable: spills
            # are generation-versioned, so resume rolls the rows back to
            # whatever slot matches the marker (_paired_fleet_anchor).
            # fleet.spill_freq > 1 amortizes the disk IO; a stop inside
            # THAT window resets rows on resume (marker behind anchor),
            # the same tradeoff as scaffold_flush_freq.
            spill_freq = int(self._fleet_cfg.get("spill_freq", 1) or 1)
            final = round_no >= self._max_iteration
            if spill_freq <= 1 or round_no % spill_freq == 0 or final:
                self.ckpt.wait()
                self.fleet_pager.flush()
                # the marker commits the DRAINED round (the pipelined
                # loop's self.state can already belong to a newer
                # dispatched chunk whose rows are not on the host yet)
                self.fleet_pager.set_round(int(round_no))
                # every checkpoint through round_no is durable after
                # the wait() above; row generations superseded at or
                # below round_no - 1 become garbage (the - 1 keeps the
                # generation a corruption fallback to .prev would need)
                self.fleet_pager.mark_durable(int(round_no) - 1)
        # one buffered-metrics flush per chunk instead of one per metric
        # line — the jsonl stream stays observable at round granularity
        # while the host tail stops paying a syscall per scalar
        flush_metrics()
        if self.scope is not None:
            # keep the on-disk trace fresh for long runs (throttled:
            # the rewrite is O(events), paid at most every
            # Tracer.FLUSH_INTERVAL_SECS)
            self.scope.flush_throttled()
            # endurance rollups flush on the same cadence: at most one
            # appended record per rollup_window rounds, then the window
            # state resets — host memory stays O(window) for any run
            # length (ISSUE 13)
            self.scope.rollup_housekeeping()
        self.run_stats["secsPerRoundHousekeeping"].append(
            time.time() - housekeeping_tic)

    # ------------------------------------------------------------------
    def _val_acc(self) -> float:
        """Validation accuracy (falls back to -loss) for RL rewards."""
        metrics = evaluate(self.task, self._eval_fn, self.state.params,
                           self._packed_eval_batches("val"), self.mesh,
                           self.engine.partition_mode)
        self.engine._note_compiles("eval_step", self._eval_fn)
        if "acc" in metrics:
            return float(metrics["acc"].value)
        return -float(metrics["loss"].value)

    def _host_round_setup(self, round_no: int):
        """Shared prologue of the host-orchestrated round paths (RL,
        SCAFFOLD): LRs, client sampling, packed batch (with the same
        per-round step bucketing the fused path uses), round rng."""
        client_lr = self.initial_lr_client * self.lr_weight
        server_lr = (self.plateau.lr if self.plateau is not None
                     else self.server_lr_schedule(round_no))
        sampled = self._sample()
        batch = pack_round_batches(
            self.train_dataset, sampled, self.batch_size,
            self._chunk_steps([sampled]), rng=self._np_rng,
            pad_clients_to=pad_to_mesh(len(sampled), self.mesh),
            desired_max_samples=self.desired_max_samples)
        self._maybe_length_bucket([batch])
        self._record_staged_bytes([batch], 1)
        self._record_padding_efficiency([batch])
        rng = self._next_rng()
        return client_lr, server_lr, batch, rng

    def _run_scaffold_round(self, round_no: int) -> None:
        """One SCAFFOLD round (``strategies/scaffold.py``): gather per-client
        control offsets ``c - c_i``, run the drift-corrected payload program,
        aggregate with sample-count weights, then update the controls
        host-side from the per-client pseudo-gradients (option II)."""
        client_lr, server_lr, batch, rng = self._host_round_setup(round_no)

        offsets = (self.scaffold_device.offsets(batch.client_ids)
                   if self.scaffold_device is not None else
                   self.scaffold_store.offsets(batch.client_ids))
        pgs, ws, tls, stats = self.engine.client_payloads(
            self.state, batch, client_lr, rng, grad_offsets=offsets,
            leakage_threshold=self.max_allowed_leakage)
        self.state = self.engine.apply_custom_weights(self.state, pgs, ws,
                                                      server_lr)

        # ONE bundled fetch for everything that exists at collect time
        # (weights + stats + losses); c_norm is PRODUCED by the control
        # update below, so it cannot ride this bundle
        ws_np, stats_np, tls_np = jax.device_get((ws, stats, tls))
        ws_np = np.asarray(ws_np)
        epochs = int(self.config.client_config.get("num_epochs", 1) or 1)
        # real local steps per client: steps with >= 1 real sample, per epoch
        steps = (batch.sample_mask.sum(axis=2) > 0).sum(axis=1) * epochs
        # invalidate the marker while the control files mutate: a crash
        # mid-update must read as a mismatch on resume, not as round N
        self.scaffold_store.set_round(-1)
        if self.scaffold_device is not None:
            # ---- in-program control update: the [K, n_params] payload
            # stack never visits the host; flush() writes the durable
            # copies when the marker commits ----
            c_norm = self.scaffold_device.update(
                batch.client_ids, steps, pgs, ws, ws_np, client_lr,
                total_clients=len(self.train_dataset))
            # the device branch's `‖c‖` only exists after the update —
            # a post-bundle scalar fetch is the price of keeping the
            # [K, n_params] control math on device
            # flint: disable=transfer-budget c_norm is produced by the control update, after the tail bundle
            c_norm = jax.device_get(c_norm)
        else:
            # ---- host-side control update (exact per-client math) ----
            # flint: disable=transfer-budget host-control branch only; bundling pgs would fetch [K, n_params] on the device branch too
            pgs_np = jax.device_get(pgs)
            k = len(batch.client_ids)
            # [K, n_params] in ravel_pytree order: tree.leaves order, each
            # leaf C-order — one concatenate, no per-client round-trips
            pgs_flat = np.concatenate(
                [np.asarray(leaf).reshape(k, -1)
                 for leaf in jax.tree.leaves(pgs_np)], axis=1)
            self.strategy.update_controls(
                self.scaffold_store, batch.client_ids, steps, pgs_flat,
                client_lr, total_clients=len(self.train_dataset),
                weights=ws_np)
            c_norm = float(np.linalg.norm(self.scaffold_store.c))

        # the tail below reads only the bundled fetch from collect time.
        # The -1 sentinel stays in place until _round_housekeeping
        # commits the marker AFTER the paired model checkpoint is
        # durable — resume keeps the controls whenever a matching
        # checkpoint exists and resets only on a crash inside the round
        # window
        self._process_privacy_stats(stats_np, round_no,
                                    client_mask=batch.client_mask)
        tls_np = np.asarray(tls_np)
        n_real = max(float((batch.client_ids >= 0).sum()), 1.0)
        log_metric("Training loss",
                   float(tls_np.sum() / n_real), step=round_no)
        log_metric("Aggregated weights", float(ws_np.sum()), step=round_no)
        log_metric("Control norm (server c)", float(c_norm),
                   step=round_no)  # latest-checkpoint save: housekeeping
        if self.scope is not None:
            # host-side bus publish of the already-fetched c_norm (the
            # device branch's post-update scalar fetch, or the host
            # branch's python float) — a counter sample, no new transfer
            self.scope.devbus_host("scaffold_c_norm", float(c_norm),
                                   step=round_no)

    # ------------------------------------------------------------------
    def _run_ef_round(self, round_no: int) -> None:
        """One error-feedback quantized round (``strategies/ef_quant.py``):
        collect per-client payloads (post local-DP transform), fold in the
        stored residuals, quantize, aggregate the quantized payloads with
        the strategy weights, and persist ``corrected - q`` per client."""
        client_lr, server_lr, batch, rng = self._host_round_setup(round_no)
        # the residual store keeps ONE row per client: a duplicate id in a
        # round batch would aggregate both quantized payloads but keep only
        # the last slot's residual, silently losing the other occurrence's
        # compression error.  Sampling is without replacement, so this is
        # a contract check, not a code path.
        real_ids = np.asarray(batch.client_ids)
        real_ids = real_ids[real_ids >= 0]
        if len(np.unique(real_ids)) != len(real_ids):
            raise ValueError(
                "ef_quant round batch contains duplicate client ids "
                f"({sorted(real_ids.tolist())}); per-client EF residuals "
                "require without-replacement sampling")
        pgs, ws, tls, stats = self.engine.client_payloads(
            self.state, batch, client_lr, rng,
            leakage_threshold=self.max_allowed_leakage)

        # per-round threshold annealing (the fused path's quant_anneal
        # semantics, logged at the same metric name)
        thresh = self.strategy.next_threshold()
        if self.strategy.quant_anneal != 1.0:
            log_metric("Quantization Thresh.", thresh, step=round_no)
        if self.scope is not None:
            # host-side bus publish: the annealed threshold is a host
            # float (no device value involved)
            self.scope.devbus_host("ef_quant_thresh", float(thresh),
                                   step=round_no)
        leaves = jax.tree.leaves(pgs)
        treedef = jax.tree.structure(pgs)
        shapes = [l.shape[1:] for l in leaves]
        sizes = [int(np.prod(sh)) for sh in shapes]
        if not hasattr(self, "_ef_step_fn"):
            strategy = self.strategy

            def step(leaves_in, residuals, thresh):
                flat = jnp.concatenate(
                    [l.reshape(l.shape[0], -1) for l in leaves_in], axis=1)
                q, new_res = strategy.ef_step(flat, residuals, thresh)
                outs, off = [], 0
                for sh, n in zip(shapes, sizes):
                    outs.append(q[:, off:off + n].reshape((-1,) + sh))
                    off += n
                return outs, new_res

            self._ef_step_fn = jax.jit(step)
        residuals = (self.ef_device.rows(batch.client_ids)
                     if self.ef_device is not None else
                     self.ef_store.rows(batch.client_ids))
        # invalidate the marker while residual files mutate: a crash
        # inside the round window must read as a mismatch on resume
        self.ef_store.set_round(-1)
        q_leaves, new_res = self._ef_step_fn(
            leaves, residuals, jnp.asarray(thresh, jnp.float32))
        q_tree = jax.tree.unflatten(treedef, q_leaves)
        self.state = self.engine.apply_custom_weights(self.state, q_tree,
                                                      ws, server_lr)

        # ONE bundled fetch for the EF tail (weights + stats + losses —
        # the same single-transfer discipline as the scaffold round)
        ws_np, stats_np, tls_np = jax.device_get((ws, stats, tls))
        ws_np = np.asarray(ws_np)
        if self.ef_device is not None:
            # new_res and ws stay on device; the scatter gates on
            # participation (id >= 0, w > 0) in-program
            self.ef_device.update(batch.client_ids, new_res, ws, ws_np)
        else:
            # dropped clients (w == 0) contributed nothing: their residual
            # must not absorb this round's uncompressed payload
            keep = (np.asarray(batch.client_ids) >= 0) & (ws_np > 0)
            # flint: disable=transfer-budget host-store branch only; bundling new_res would fetch the [K, n_params] residual stack on the device branch too
            new_res_np = np.asarray(jax.device_get(new_res))
            self.ef_store.update(batch.client_ids, new_res_np, keep)

        self._process_privacy_stats(stats_np, round_no,
                                    client_mask=batch.client_mask)
        tls_np = np.asarray(tls_np)
        n_real = max(float((batch.client_ids >= 0).sum()), 1.0)
        log_metric("Training loss",
                   float(tls_np.sum() / n_real), step=round_no)
        log_metric("Aggregated weights", float(ws_np.sum()), step=round_no)

    # ------------------------------------------------------------------
    def _run_rl_round(self, round_no: int) -> None:
        """One RL-assisted round (reference ``core/strategies/dga.py:286-406``):
        collect per-client payloads once, aggregate with both the strategy
        weights and the RL-estimated weights, keep whichever validates
        better, reward the policy, train the DQN."""
        client_lr, server_lr, batch, rng = self._host_round_setup(round_no)

        pgs, ws, _tls, stats = self.engine.client_payloads(
            self.state, batch, client_lr, rng,
            leakage_threshold=self.max_allowed_leakage)
        # ONE fetch for everything the RL head reads — per-field
        # device_get of stats members paid a transfer per stat
        ws_np, stats_np = jax.device_get((ws, stats))
        ws_np = np.asarray(ws_np)
        k = int((batch.client_ids >= 0).sum())
        state_vec = np.concatenate([
            ws_np[:k],
            np.asarray(stats_np["mag"])[:k],
            np.asarray(stats_np["mean"])[:k],
            np.asarray(stats_np["var_corrected"])[:k]])

        # candidate A: strategy weights; candidate B: RL weights
        baseline_state = self.engine.apply_custom_weights(
            self.state, pgs, ws, server_lr)
        action = self.rl.forward(state_vec)
        rl_w = self.rl.weights_from_action(action)
        rl_w_full = np.zeros_like(ws_np)
        rl_w_full[:k] = rl_w[:k] if len(rl_w) >= k else \
            np.pad(rl_w, (0, k - len(rl_w)))
        rl_state = self.engine.apply_custom_weights(
            self.state, pgs, rl_w_full, server_lr)

        self.state = baseline_state
        baseline_acc = self._val_acc()
        self.state = rl_state
        rl_acc = self._val_acc()

        reward, keep_rl = self.rl.compute_reward(
            baseline_acc, rl_acc,
            bool(self.config.lookup("server_config.RL.marginal_update_RL",
                                    True)))
        self.state = rl_state if keep_rl else baseline_state
        log_metric("RL Rewards", reward, step=round_no)
        log_metric("Val acc (baseline vs RL)",
                   {"baseline": baseline_acc, "rl": rl_acc}, step=round_no)
        # attack metrics + adaptive leakage threshold, same as the fused
        # and scaffold paths — without this the adaptive threshold could
        # never update and the leakage-based dropping would stay inert
        self._process_privacy_stats(stats_np, round_no,
                                    client_mask=batch.client_mask)
        self.rl.train(state_vec, action, reward)
        self.rl.save()
        log_metric("RL Running Loss", self.rl.running_loss, step=round_no)

    # ------------------------------------------------------------------
    def _chunk_client_masks(self, batches) -> np.ndarray:
        """``[R, K]`` live-client mask of one chunk for the privacy-stat
        distribution.  Bucketed chunks concatenate each round's bucket
        masks in ascending-bucket order — the SAME layout the finalize
        program concatenates its per-client vectors in — then zero-pad
        rounds to the chunk max exactly like
        :meth:`~msrflute_tpu.engine.round.BucketedStats.fetch`."""
        rows = []
        for entry in batches:
            if isinstance(entry, list):
                rows.append(np.concatenate(
                    [b.client_mask for b in entry]))
            else:
                rows.append(np.asarray(entry.client_mask))
        width = max(r.shape[0] for r in rows)
        return np.stack([
            r if r.shape[0] == width
            else np.concatenate([r, np.zeros(width - r.shape[0],
                                             r.dtype)])
            for r in rows])

    def _process_privacy_stats(self, stats, round_no: int,
                               client_mask=None) -> None:
        """Log attack metrics + adapt the leakage threshold (reference
        ``core/server.py:390-409``: the new threshold is the configured
        quantile of this chunk's per-client leakage values).  ``client_mask``
        [R, K] excludes mesh-padding lanes from the distribution."""
        if "privacy_dropped" not in stats:
            return
        real = (np.asarray(client_mask).ravel() > 0 if client_mask is not None
                else None)

        def _select(key):
            vals = np.asarray(stats[key]).ravel()
            if real is not None and real.shape == vals.shape:
                vals = vals[real]
            return vals[np.isfinite(vals)]

        log_metric("Dropped clients", float(_select("privacy_dropped").sum()),
                   step=round_no)
        for key, name in (("privacy_overlap", "Extracted indices percentage"),
                          ("privacy_leakage", "Practical epsilon (Max leakage)"),
                          ("privacy_above_rank", "Words percentage above rank")):
            if key in stats:
                finite = _select(key)
                if finite.size:
                    log_metric(name, float(finite.max()), step=round_no)
        if self.adaptive_leakage is not None and "privacy_leakage" in stats:
            values = np.sort(_select("privacy_leakage"))
            if values.size:
                idx = min(int(self.adaptive_leakage * values.size),
                          values.size - 1)
                self.max_allowed_leakage = float(values[idx])
                print_rank(f"updated leakage threshold to "
                           f"{self.max_allowed_leakage}")

    # ------------------------------------------------------------------
    _last_val: MetricsDict = {}

    def _split_cfg(self, split: str):
        dc = self.config.server_config.data_config
        return dc.val if split == "val" else dc.test

    def _packed_eval_batches(self, split: str):
        """Packed ``[T, B, ...]`` eval grid for a split — cached AS STAGED
        DEVICE ARRAYS: eval data is static across rounds, so both the host
        packing and the host->device transfer happen once per split; every
        later eval's ``device_put`` on the already-placed arrays is a
        no-op (the RL path evaluates twice per round, and on a remote-
        attached chip the re-transfer would otherwise dominate eval)."""
        batches = self._eval_batches_cache.get(split)
        if batches is None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            dataset = self.val_dataset if split == "val" else self.test_dataset
            bs = int(self._split_cfg(split).get("batch_size",
                                                self.batch_size))
            batches = pack_eval_batches(
                dataset, bs,
                pad_steps_to_multiple_of=self.mesh.shape[CLIENTS_AXIS])
            spec = (P(CLIENTS_AXIS) if self.engine.partition_mode ==
                    "shard_map" else P())
            sharding = NamedSharding(self.mesh, spec)
            # flint: disable=put-loop eval batches staged once and cached across evals
            batches = {k: jax.device_put(v, sharding)
                       for k, v in batches.items()}
            self._eval_batches_cache[split] = batches
        return batches

    def _maybe_eval(self, split: str, round_no: int, force: bool = False) -> bool:
        dataset = self.val_dataset if split == "val" else self.test_dataset
        if dataset is None or len(dataset) == 0:
            return False
        with self._tspan("eval", split=split, round=round_no):
            metrics = evaluate(self.task, self._eval_fn, self.state.params,
                               self._packed_eval_batches(split), self.mesh,
                               self.engine.partition_mode,
                               telemetry=self.scope)
        # eval compiles join the always-on compile log (and so the
        # recompile counter the storm watchdog + scorecard gate on) —
        # an eval-grid shape churn must not hide from the sentinel
        self.engine._note_compiles("eval_step", self._eval_fn)
        for name, metric in metrics.items():
            log_metric(f"{split.capitalize()} {name}", metric.value, step=round_no)
        if self._split_cfg(split).get("wantLogits", False):
            self._dump_predictions(split, round_no)
        if self._split_cfg(split).get("per_user_stats", False):
            self._log_per_user_stats(split, round_no, dataset)

        improved = False
        if split == "val":
            self._last_val = metrics
            for name, metric in metrics.items():
                if not np.isfinite(metric.value):
                    # eval-side non-finite guard, host half: a NaN/Inf
                    # metric must never enter best_val (it would poison
                    # every later is_better_than comparison and the
                    # fall-back-to-best target) — today's value simply
                    # doesn't compete
                    emit_event(self.scope, "eval_nonfinite_skipped",
                               split=split, metric=name, round=round_no,
                               value=str(metric.value))
                    continue
                prev = self.best_val.get(name)
                if prev is None or metric.is_better_than(prev):
                    self.best_val[name] = metric
                    self.ckpt.save_best(self.state, name)
                    if name == self.best_model_criterion:
                        improved = True
            # convergence-tier crossing (traffic.target_accuracy): the
            # FIRST val eval at/above the target pins the round — the
            # rounds_to_target_accuracy bench.py records and `scope
            # trend` gates alongside secs_per_round
            if self.target_accuracy is not None and \
                    self.rounds_to_target_accuracy is None:
                acc = metrics.get("acc")
                if acc is not None and np.isfinite(acc.value) and \
                        float(acc.value) >= self.target_accuracy:
                    self.rounds_to_target_accuracy = int(round_no)
                    emit_event(self.scope, "target_accuracy_reached",
                               round=round_no, acc=float(acc.value),
                               target=self.target_accuracy)
        return improved

    def _log_per_user_stats(self, split: str, round_no: int,
                            dataset) -> None:
        """Per-user accuracy dispersion when the split's data_config sets
        ``per_user_stats`` — the fairness observability the aggregate
        metric hides (and what q-FFL/AFL-style strategies optimize):
        worst / p10 / p50 / p90 / std of per-user accuracy, plus the
        evaluated-user count.  Classification-style tasks only: needs
        ``task.apply`` producing per-sample class logits AND ``y`` labels
        in the eval grid (BERT MLM has ``apply`` but no ``y``; sequence
        tasks have neither) — anything else warns and skips."""
        batches = self._packed_eval_batches(split)
        if not hasattr(self.task, "apply") or "y" not in batches:
            print_rank(f"per_user_stats set for {split} but task "
                       f"{type(self.task).__name__} is not "
                       "classification-style (needs apply() + y labels); "
                       "skipping", loglevel=logging.WARNING)
            return
        from .evaluation import build_per_user_eval_fn, per_user_accuracy
        if split not in self._per_user_fns:
            self._per_user_fns[split] = build_per_user_eval_fn(
                self.task, self.mesh, len(dataset),
                self.engine.partition_mode)
        accs = per_user_accuracy(self._per_user_fns[split],
                                 self.state.params, batches,
                                 self.mesh, self.engine.partition_mode)
        accs = accs[~np.isnan(accs)]
        if accs.size == 0:
            return
        cap = split.capitalize()
        log_metric(f"{cap} acc (worst user)", float(accs.min()),
                   step=round_no)
        for pct in (10, 50, 90):
            log_metric(f"{cap} acc (user p{pct})",
                       float(np.percentile(accs, pct)), step=round_no)
        log_metric(f"{cap} acc (user std)", float(accs.std()),
                   step=round_no)
        log_metric(f"{cap} acc (users evaluated)", int(accs.size),
                   step=round_no)

    def _dump_predictions(self, split: str, round_no: int,
                          topk: int = 3) -> None:
        """Per-sample prediction dump when the split's data_config sets
        ``wantLogits`` (reference ``core/client.py:156`` +
        ``nlg_gru/model.py:113-130``: eval returns output payloads).
        One JSON line per real sample -> ``predictions_<split>_r<N>.jsonl``.

        Deliberate cost: this is a SECOND forward over the eval grid, kept
        separate from the metric eval (whose contract is psum'd scalar
        sums, not per-sample payloads) — it only runs on wantLogits evals.
        """
        import json as _json

        task = self.task
        seq_fn = getattr(task, "topk_predictions", None)
        cls_fn = getattr(task, "predict", None)
        if seq_fn is None and cls_fn is None:
            print_rank(f"wantLogits set for {split} but task "
                       f"{type(task).__name__} exposes neither "
                       "topk_predictions nor predict — no dump written",
                       loglevel=logging.WARNING)
            return
        batches = self._packed_eval_batches(split)
        if not hasattr(self, "_pred_fns"):
            self._pred_fns = {}
        fn = self._pred_fns.get(split)
        if fn is None:
            if seq_fn is not None:
                fn = jax.jit(lambda p, b: seq_fn(p, b, topk))
            else:
                fn = jax.jit(cls_fn)
            self._pred_fns[split] = fn

        path = os.path.join(self.ckpt.model_dir,
                            f"predictions_{split}_r{round_no}.jsonl")
        T = batches["sample_mask"].shape[0]
        # the cache holds staged DEVICE arrays; pull the two bookkeeping
        # grids to host in ONE fetch instead of one transfer per grid
        # (and none per step)
        mask_np, uids_np = jax.device_get(
            (batches["sample_mask"], batches["user_idx"]))
        mask_np = np.asarray(mask_np) > 0
        uids_np = np.asarray(uids_np)
        # tmp + os.replace: the dump streams one row per sample, so a
        # crash mid-loop would otherwise leave a silently-truncated
        # predictions file at the advertised path
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for t in range(T):
                mask = mask_np[t]
                if not mask.any():
                    continue  # mesh-padding step: skip the forward entirely
                batch = {k: v[t] for k, v in batches.items()
                         if k != "user_idx"}
                out = jax.device_get(fn(self.state.params, batch))
                uids = uids_np[t]
                for i in np.flatnonzero(mask):
                    if seq_fn is not None:
                        top_p, top_ids, labels = out
                        row = {"user": int(uids[i]),
                               "topk_ids": top_ids[i].tolist(),
                               "topk_probs": np.round(
                                   top_p[i], 6).tolist(),
                               "labels": labels[i].tolist()}
                    else:
                        logits, pred, labels = out
                        row = {"user": int(uids[i]),
                               "pred": int(pred[i]),
                               "label": int(labels[i]),
                               "logits": np.round(logits[i], 6).tolist()}
                    fh.write(_json.dumps(row) + "\n")
        os.replace(tmp, path)
        print_rank(f"wrote {split} predictions to {path}")

    def _fall_back(self) -> None:
        """Reload the best checkpoint, preserving current LR weight
        (reference ``core/server.py:561-578``)."""
        restored = self.ckpt.load_best(self.state, self.best_model_criterion)
        if restored is not None:
            self.state = ServerState(restored.params, restored.opt_state,
                                     restored.strategy_state, self.state.round)
            print_rank("fell back to previous best model")
            if self.scaffold_store is not None:
                # controls accumulated since that checkpoint belong to the
                # abandoned trajectory; restart control estimation from
                # zero (the paper's init) rather than bias the restored
                # params with stale drift corrections
                if self.scaffold_device is not None:
                    self.scaffold_device.reset()  # also resets the store
                else:
                    self.scaffold_store.reset()
                print_rank("reset SCAFFOLD controls after fallback")
            if self.ef_store is not None:
                # residuals accumulated since that checkpoint carry the
                # abandoned trajectory's compression error
                if self.ef_device is not None:
                    self.ef_device.reset()  # also resets the store
                else:
                    self.ef_store.reset()
                print_rank("reset EF residuals after fallback")

    def _log_timing(self) -> None:
        """Timing summary (reference ``run_stats``, ``core/server.py:492-521``)
        — percentiles as well as means: tail rounds are what a wall-clock
        budget actually pays for."""
        for key, values in self.run_stats.items():
            if values:
                log_metric(f"{key} (mean)", float(np.mean(values)))
                log_metric(f"{key} (p50)", float(np.percentile(values, 50)))
                log_metric(f"{key} (p95)", float(np.percentile(values, 95)))


def select_server(server_type: str):
    """Reference ``select_server`` (``core/server.py:581-597``):
    ``personalization`` -> PersonalizationServer, else OptimizationServer."""
    if (server_type or "").lower() == "personalization":
        from .personalization import PersonalizationServer
        return PersonalizationServer
    return OptimizationServer
