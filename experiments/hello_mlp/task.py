"""hello_mlp — the scenario-authoring example task (docs/scenarios.md).

A plugin task folder needs exactly one hook: ``make_task(model_config)`` in
``task.py`` (the TPU-native analogue of the reference's dynamically loaded
``experiments/<task>/model.py`` + ``dataloaders/``, reference
``doc/sphinx/scenarios.rst`` + ``experiments/__init__.py:8-43``).  Everything
else — datasets, metrics — hangs off the returned BaseTask.
"""
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from msrflute_tpu.models.cv import ClassificationTask
from msrflute_tpu.utils.metrics import Metric


class _MLP(nn.Module):
    hidden: int = 64
    num_classes: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class HelloMLPTask(ClassificationTask):
    """ClassificationTask + one custom metric.

    Custom metrics are sum-form device stats (``eval_stats``) finalized to
    ``Metric(value, higher_is_better)`` host-side (``finalize_metrics``) —
    the TPU translation of the reference's ``inference()`` returning
    ``{"custom": {"value": v, "higher_is_better": True}}``
    (``doc/sphinx/scenarios.rst`` "Implement new metrics").
    """

    def eval_stats(self, params, batch):
        stats = super().eval_stats(params, batch)
        logits = self.apply(params, batch["x"])
        labels = batch["y"].astype(jnp.int32)
        top2 = jnp.argsort(logits, axis=-1)[:, -2:]
        hit = jnp.any(top2 == labels[:, None], axis=-1).astype(jnp.float32)
        stats["top2_sum"] = jnp.sum(hit * batch["sample_mask"])
        return stats

    def finalize_metrics(self, sums):
        metrics = super().finalize_metrics(sums)
        if "top2_sum" in sums:
            metrics["top2_acc"] = Metric(
                float(sums["top2_sum"]) / max(float(sums["sample_count"]), 1.0),
                higher_is_better=True)
        return metrics


def make_task(model_config) -> HelloMLPTask:
    input_dim = int(model_config.get("input_dim", 16))
    num_classes = int(model_config.get("num_classes", 3))
    return HelloMLPTask(
        _MLP(hidden=int(model_config.get("hidden", 64)),
             num_classes=num_classes),
        example_shape=(input_dim,),
        name="hello_mlp",
        num_classes=num_classes)
