"""Model-specific config defaults for hello_mlp (docs/scenarios.md step 3).

The registry merges ``<model_type>Config.defaults`` into the model config
for keys the YAML did not set (reference ``core/config.py:100-116``).
"""


class HELLOMLPConfig:
    defaults = {
        "input_dim": 16,
        "num_classes": 3,
        "hidden": 64,
    }
