"""CLI launcher — run a federated simulation from a YAML config.

Parity target: reference ``e2e_trainer.py`` (invoked under
``torch.distributed.run`` with ``-config -dataPath -outputPath -task``,
``e2e_trainer.py:198-253``).  The TPU build is single-controller: no
process launcher, no backend flag — the mesh spans whatever devices JAX
sees (multi-host via ``jax.distributed``, see
``msrflute_tpu.parallel.mesh.maybe_init_distributed``).

Usage:
    python e2e_trainer.py -config cfg.yaml -dataPath ./data \
        -outputPath ./out -task cv_lr_mnist
"""

from __future__ import annotations

import argparse
import os
import shutil

import yaml


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-config", required=True)
    ap.add_argument("-dataPath", default=None)
    ap.add_argument("-outputPath", default="./output")
    ap.add_argument("-task", default=None)
    ap.add_argument("-num_skip_decoding", default=-1, type=int)  # parity arg
    ap.add_argument("-backend", default="xla")  # parity arg; always XLA here
    args = ap.parse_args()

    from msrflute_tpu.config import FLUTEConfig
    from msrflute_tpu.engine import select_server
    from msrflute_tpu.models import make_task
    from msrflute_tpu.parallel import make_mesh
    from msrflute_tpu.parallel.mesh import maybe_init_distributed
    from msrflute_tpu.tasks import build_server_train_dataset, build_task_datasets
    from msrflute_tpu.utils import init_logging, print_rank

    maybe_init_distributed()

    # output/models/log dir setup + config copy (reference e2e_trainer.py:222-235)
    os.makedirs(args.outputPath, exist_ok=True)
    model_dir = os.path.join(args.outputPath, "models")
    log_dir = os.path.join(args.outputPath, "log")
    os.makedirs(model_dir, exist_ok=True)
    init_logging(log_dir)
    shutil.copyfile(args.config,
                    os.path.join(args.outputPath, os.path.basename(args.config)))

    with open(args.config) as fh:
        raw = yaml.safe_load(fh)
    cfg = FLUTEConfig.from_dict(raw)
    cfg.task = args.task or cfg.task
    cfg.data_path = args.dataPath or cfg.data_path
    cfg.output_path = args.outputPath
    cfg.validate(cfg.data_path)

    # plugin-folder resolution (reference loads experiments/<task>/ by the
    # -task name, utils/dataloaders_utils.py:9-23): an explicit
    # model_folder resolves against cwd, the config file's directory, then
    # the repo root; without one, experiments/<task>/task.py is used when
    # it exists, so `-task mytask` alone finds the plugin
    repo_root = os.path.dirname(os.path.abspath(__file__))
    folder = cfg.model_config.get("model_folder")
    if folder:
        for base in ("", os.path.dirname(os.path.abspath(args.config)),
                     repo_root):
            cand = os.path.join(base, folder) if base else folder
            if os.path.isdir(cand):
                cfg.model_config["model_folder"] = os.path.abspath(cand)
                break
    elif cfg.task:
        cand = os.path.join(repo_root, "experiments", cfg.task)
        if os.path.exists(os.path.join(cand, "task.py")):
            cfg.model_config["model_folder"] = cand

    # applied-defaults report (reference core/config.py:771-779 prints the
    # diff between the user YAML and the config with defaults filled in)
    from msrflute_tpu.schema import applied_defaults
    defaults = {k: v for k, v in applied_defaults(raw, cfg).items()
                if k not in ("task", "data_path", "output_path")}  # CLI-assigned
    if defaults:
        print_rank("config defaults applied: "
                   + ", ".join(f"{k}={v!r}" for k, v in sorted(defaults.items())))

    # persistent XLA compilation cache (server_config.compilation_cache_dir):
    # repeat runs of the same protocol skip the tens-of-seconds first
    # compile — worth it on TPU, harmless elsewhere
    cache_dir = cfg.server_config.get("compilation_cache_dir")
    if cache_dir:
        from msrflute_tpu.utils.backend import enable_compilation_cache
        enable_compilation_cache(cache_dir)

    task = make_task(cfg.model_config)
    train_ds, val_ds, test_ds = build_task_datasets(cfg, task)
    print_rank(f"task={cfg.task} users={len(train_ds)} "
               f"val={len(val_ds) if val_ds else 0} "
               f"test={len(test_ds) if test_ds else 0}")

    # experiment properties at startup (reference log_run_properties,
    # e2e_trainer.py:40-74 — AzureML run properties become metrics.jsonl)
    from msrflute_tpu.utils import log_metric
    log_metric("run_properties", {
        "task": cfg.task,
        "model_type": cfg.model_config.get("model_type"),
        "strategy": cfg.strategy,
        "max_iteration": cfg.server_config.get("max_iteration"),
        "num_clients_per_iteration":
            cfg.server_config.get("num_clients_per_iteration"),
        "initial_lr_client": cfg.server_config.get("initial_lr_client"),
        "server_optimizer": cfg.server_config.optimizer_config.get("type"),
        "client_optimizer": cfg.client_config.optimizer_config.get("type"),
        "num_users": len(train_ds),
        "dp_enabled": bool(cfg.dp_config and
                           (cfg.dp_config.get("enable_local_dp") or
                            cfg.dp_config.get("enable_global_dp"))),
    })

    mesh = make_mesh(model_axis_size=int(cfg.mesh_config.get("model_axis_size", 1)))
    server_cls = select_server(cfg.server_config.get("type", "optimization"))
    server = server_cls(task, cfg, train_ds, val_dataset=val_ds,
                        test_dataset=test_ds,
                        server_train_dataset=build_server_train_dataset(cfg, task),
                        model_dir=model_dir, mesh=mesh)
    server.run()

    # graceful preemption (SIGTERM/SIGINT mid-run, or the chaos drill's
    # preempt_at_round): the server drained the in-flight round, wrote a
    # durable checkpoint + rng resume anchors, and returned.  Exit with
    # EX_TEMPFAIL (75) so schedulers re-queue the job rather than scoring
    # it as success or crash; re-launching the same command with
    # server_config.resume_from_checkpoint: true continues bit-exactly
    # (docs/RUNBOOK.md "Preemption & recovery drill").
    if getattr(server, "preempted", False):
        print_rank("exiting preempted (EX_TEMPFAIL); resume with "
                   "server_config.resume_from_checkpoint: true")
        raise SystemExit(os.EX_TEMPFAIL)


if __name__ == "__main__":
    main()
