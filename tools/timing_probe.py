"""Honest on-chip wall-time measurement for the tools/ scripts.

``jax.block_until_ready`` is NOT a trustworthy fence on the remote axon
backend: the first committed ``flash_crossover.json`` read a flat
~0.045 ms at every length/tile — dense attention fwd+bwd "in 60 us" at
L=8192 against >4 GB of HBM traffic — i.e. the call returned before the
device finished.  A host ``float()`` of a scalar result cannot lie: the
4-byte transfer completes only after the producing program does.  Cost:
one dispatch floor (~0.14 ms) per iteration, paid identically on both
sides of any comparison these tools make.

Shared by ``flash_crossover_sweep.py`` (queue job 92) and
``validate_flash_auto.py`` (queue job 98) so the timing methodology
cannot drift between the sweep and its validator.
"""

from __future__ import annotations

import time


def scalar_time(fn, *args, iters: int = 20) -> float:
    """Mean wall seconds per call of ``fn`` (which must return a SCALAR),
    fetching the value to host each iteration as the sync fence."""
    float(fn(*args))  # compile + first run
    tic = time.perf_counter()
    for _ in range(iters):
        float(fn(*args))
    return (time.perf_counter() - tic) / iters


def grad_wall(attn_fn, q, k, v, iters: int = 20) -> float:
    """Fwd+bwd wall time of ``sum(attn_fn(q,k,v)**2)`` w.r.t. all three
    inputs.  The jitted probe returns full-reduction sums of every grad —
    a scalar for :func:`scalar_time`'s fence that also keeps XLA from
    dead-code-eliminating any part of the backward pass."""
    import jax
    import jax.numpy as jnp

    def loss(q, k, v):
        return jnp.sum(attn_fn(q, k, v) ** 2)

    def probe(q, k, v):
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return (jnp.sum(dq.astype(jnp.float32)) +
                jnp.sum(dk.astype(jnp.float32)) +
                jnp.sum(dv.astype(jnp.float32)))

    return scalar_time(jax.jit(probe), q, k, v, iters=iters)
