"""Honest on-chip wall-time measurement for the tools/ scripts.

The implementation moved to :mod:`msrflute_tpu.telemetry.timing` (the
one timing source of truth — bench.py and tools/profile_round.py sit on
the same primitives); this module keeps the import path
``flash_crossover_sweep.py`` / ``validate_flash_auto.py`` were written
against.

Why a scalar fence at all: ``jax.block_until_ready`` is NOT trustworthy
on the remote axon backend — the first committed ``flash_crossover.json``
read a flat ~0.045 ms at every length/tile (the call returned before the
device finished).  A host ``float()`` of a scalar result cannot lie; see
the telemetry.timing docstrings for the full methodology.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from msrflute_tpu.telemetry.timing import grad_wall, scalar_time  # noqa: E402,F401
