"""On-chip real-data convergence: the digits accuracy protocols on TPU.

The CPU suite proves the stack LEARNS on real images
(`tests/test_accuracy_digits.py`, `tests/test_accuracy_cnn.py` — sklearn
digits standing in for MNIST under zero egress, reference accuracy story
at `/root/reference/README.md:38-41`).  This script runs the same three
protocols on the real chip and writes `digits_tpu.json`: final val
accuracy + wall-clock per family, so "learns on real data" is also a
committed *on-chip* artifact, not only a host-CPU one.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _digits():
    from sklearn.datasets import load_digits

    from msrflute_tpu.data import ArraysDataset
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    rng = np.random.default_rng(0)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    flat_val = ArraysDataset(["val"], [{"x": x[1500:], "y": y[1500:]}])
    img = x.reshape(-1, 8, 8, 1)
    img_val = ArraysDataset(["val"], [{"x": img[1500:], "y": y[1500:]}])
    flat_users, img_users = [], []
    names = [f"u{u:03d}" for u in range(100)]
    for u in range(100):
        sl = slice(u * 15, (u + 1) * 15)
        flat_users.append({"x": x[sl], "y": y[sl]})
        img_users.append({"x": img[sl], "y": y[sl]})
    return (ArraysDataset(names, flat_users), flat_val,
            ArraysDataset(names, img_users), img_val)


def _cfg(model_cfg, rounds, lr):
    from msrflute_tpu.config import FLUTEConfig
    return FLUTEConfig.from_dict({
        "model_config": model_cfg,
        "strategy": "fedavg",
        "server_config": {
            "max_iteration": rounds,
            "num_clients_per_iteration": 10,
            "initial_lr_client": lr,
            "rounds_per_step": 10,
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": rounds, "initial_val": False,
            "best_model_criterion": "acc",
            "data_config": {"val": {"batch_size": 512}},
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": lr},
            "data_config": {"train": {"batch_size": 5}},
        },
    })


def run(name, model_cfg, rounds, lr, train, val, floor):
    import jax

    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task
    from msrflute_tpu.parallel import make_mesh
    cfg = _cfg(model_cfg, rounds, lr)
    task = make_task(cfg.model_config)
    with tempfile.TemporaryDirectory() as tmp:
        server = OptimizationServer(task, cfg, train, val_dataset=val,
                                    model_dir=tmp, mesh=make_mesh(), seed=0)
        tic = time.time()
        server.train()
        jax.block_until_ready(server.state.params)
        secs = time.time() - tic
    acc = float(server.best_val["acc"].value)
    out = {"rounds": rounds, "final_val_acc": round(acc, 4),
           "floor": floor, "ok": acc > floor,
           "wall_secs": round(secs, 2)}
    print(f"[digits_tpu] {name}: {out}", file=sys.stderr)
    return out


def main() -> int:
    import jax
    assert jax.default_backend() == "tpu", jax.default_backend()
    flat_train, flat_val, img_train, img_val = _digits()
    res = {"backend": "tpu"}
    res["lr"] = run("lr", {"model_type": "LR", "num_classes": 10,
                           "input_dim": 64}, 60, 0.5,
                    flat_train, flat_val, 0.8)
    res["cnn"] = run("cnn", {"model_type": "CNN", "num_classes": 10,
                             "image_size": 8}, 30, 0.1,
                     img_train, img_val, 0.8)
    res["resnet"] = run("resnet",
                        {"model_type": "RESNET", "depth": 18,
                         "num_classes": 10, "image_size": 8,
                         "in_channels": 1,
                         "channels_per_group": 16}, 30, 0.1,
                        img_train, img_val, 0.55)
    print(json.dumps(res))
    return 0 if all(res[k]["ok"] for k in ("lr", "cnn", "resnet")) else 1


if __name__ == "__main__":
    sys.exit(main())
