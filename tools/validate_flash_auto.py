"""Validate the ringlm dense/flash "auto" policy on both crossover sides.

Reads the committed ``flash_crossover.json`` sweep (queue job 92,
``tools/flash_crossover_sweep.py``), picks the measured length just BELOW
the dense→flash crossover and the first length AT/ABOVE it, re-times both
paths at those lengths with the production tile defaults, and checks that
``models/ringlm.py::_resolve_flash("auto", L)`` — i.e. the shipped
``FLASH_AUTO_MIN_LEN`` constant — selects the measured-faster branch on
each side.  Exit 0 only if the policy is right on both sides; the JSON on
stdout carries the measurements either way.

Usage (chip job)::

    python tools/validate_flash_auto.py [flash_crossover.json]
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.timing_probe import grad_wall  # noqa: E402


def main() -> int:
    import jax
    import jax.numpy as jnp

    assert jax.default_backend() == "tpu", jax.default_backend()

    path = sys.argv[1] if len(sys.argv) > 1 else "flash_crossover.json"
    from tools.calibrate_flash import analyze
    from msrflute_tpu.models.ringlm import FLASH_AUTO_MIN_LEN, _resolve_flash
    from msrflute_tpu.ops.pallas_attention import flash_attention

    try:
        cal = analyze(path)
        if not cal["lengths"]:
            raise ValueError("sweep artifact has no length rows")
    except Exception as exc:
        # unusable sweep (empty/truncated from a timed-out job 92): rc 2
        # so the queue job can distinguish "re-arm" from "policy wrong"
        print(json.dumps({"error": f"{type(exc).__name__}: {exc}",
                          "artifact": path}))
        return 2
    lengths = sorted(cal["lengths"])
    cross = cal.get("recommended_flash_auto_min_len") or cal.get("crossover")
    below = max((L for L in lengths if L < FLASH_AUTO_MIN_LEN), default=None)
    above = min((L for L in lengths if L >= FLASH_AUTO_MIN_LEN), default=None)

    B, H, D = 4, 4, 64  # the sweep's RingLM head geometry
    rng = np.random.default_rng(0)

    def dense(q, k, v):
        L = q.shape[1]
        scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
        scores = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
        mask = jnp.tril(jnp.ones((L, L), bool))
        scores = jnp.where(mask[None, None], scores,
                           jnp.finfo(scores.dtype).min)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhlm,bmhd->blhd", p, v)

    def flash(q, k, v):
        # force_flash: this arm must TIME THE KERNEL — the dispatch gate
        # substituting dense here would validate the crossover constant
        # against dense-vs-dense timings (vacuously)
        return flash_attention(q, k, v, causal=True, force_flash=True)

    out = {"backend": "tpu", "flash_auto_min_len": FLASH_AUTO_MIN_LEN,
           "sweep_crossover": cross, "sides": {}}
    ok = True
    for side, L in (("below", below), ("above", above)):
        if L is None:
            # constant sits outside the measured range on this side —
            # nothing to validate there (e.g. flash wins everywhere)
            out["sides"][side] = None
            continue
        q, k, v = (jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.bfloat16)
                   for _ in range(3))
        dms = grad_wall(dense, q, k, v) * 1e3
        fms = grad_wall(flash, q, k, v) * 1e3
        picked_flash = _resolve_flash("auto", L)
        # near the crossover the two paths are close BY CONSTRUCTION;
        # within a 5% band either pick is correct (shared-tunnel timing
        # jitter must not fail the queue job over a sign flip)
        within_band = abs(dms - fms) <= 0.05 * max(dms, fms)
        correct = within_band or picked_flash == (fms < dms)
        ok &= correct
        out["sides"][side] = {
            "length": L, "dense_fwd_bwd_ms": round(dms, 3),
            "flash_fwd_bwd_ms": round(fms, 3),
            "auto_picks": "flash" if picked_flash else "dense",
            "measured_faster": "flash" if fms < dms else "dense",
            "within_5pct_band": within_band,
            "auto_correct": correct,
        }
    out["ok"] = ok
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
