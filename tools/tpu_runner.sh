#!/bin/bash
# Serialized TPU job runner (round 3).  The chip sits behind a single-client
# tunnel that WEDGES if a claiming process is killed — so: one job at a
# time, no kill timeouts, poll with a real matmul until the chip answers.
# Jobs are tools/tpu_jobs.d/NN-*.sh, run in sort order, each exactly once
# (marker: <job>.done holding the exit code).  Append jobs while running.
cd /root/repo
log(){ echo "[tpu_runner $(date +%H:%M:%S)] $*" >> tpu_runner.log; }
# Sanction this process tree to claim the tunnel: the framework's
# tunnel-claim guardrail (utils/backend.py::guard_tunnel_claim) refuses
# axon init in agent shells UNLESS this marker is set, so queue jobs are
# the only agent-launched path to the chip.
export MSRFLUTE_CHIP_JOB=1
# Probe with a timeout: while a stale claim is pending server-side a
# probe HANGS instead of failing fast (observed live round 4), and a
# timeout-less probe then blocks the whole runner loop.  SIGTERM only —
# the graceful path; a probe that never acquired the claim is safe to
# stop.
probe(){ timeout -s TERM -k 30 120 python - <<'PYEOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
jax.block_until_ready(jnp.ones((128, 128)) @ jnp.ones((128, 128)))
PYEOF
}
log "runner started (pid $$)"
# Single-client tunnel: near the round's end the DRIVER runs bench.py on
# the chip; the runner must not be mid-job holding the claim then.  Stop
# starting new jobs after this UTC hour (driver window); touch
# tools/tpu_jobs.d/.no_deadline to disable.
DEADLINE_H=${TPU_RUNNER_DEADLINE_H:-17}
WINDOW_END_H=${TPU_RUNNER_WINDOW_END_H:-24}
if [ "$DEADLINE_H" -ge "$WINDOW_END_H" ]; then
  log "DEADLINE_H=$DEADLINE_H >= WINDOW_END_H=$WINDOW_END_H: guard disabled"
fi
while true; do
  if [ ! -f tools/tpu_jobs.d/.no_deadline ] && \
     [ "$(date -u +%H)" -ge "$DEADLINE_H" ] && \
     [ "$(date -u +%H)" -lt "$WINDOW_END_H" ]; then
    log "driver bench window (>= 0${DEADLINE_H}:00 UTC); not starting new jobs"
    sleep 300; continue
  fi
  job=""
  for j in $(ls tools/tpu_jobs.d/*.sh 2>/dev/null | sort); do
    [ -f "$j.done" ] || { job="$j"; break; }
  done
  if [ -z "$job" ]; then sleep 120; continue; fi
  until probe; do
    log "chip down (probe failed); sleeping 180s"; sleep 180
    # the probe-wait can span INTO the driver window: re-evaluate the
    # guard between probes or a mid-window chip recovery would start a
    # job and hold the single-client claim against the driver's bench
    if [ ! -f tools/tpu_jobs.d/.no_deadline ] && \
       [ "$(date -u +%H)" -ge "$DEADLINE_H" ] && \
       [ "$(date -u +%H)" -lt "$WINDOW_END_H" ]; then
      continue 2
    fi
  done
  log "chip up; running $job"
  bash "$job" >> tpu_runner.log 2>&1
  rc=$?
  echo "$rc" > "$job.done"
  log "job $job rc=$rc"
done
