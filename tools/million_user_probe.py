"""Million-user host-loader probe — the "millions of clients" evidence.

The reference claims million-client scale (``/root/reference/README.md:9``)
but its loaders materialize every user's samples; this framework's scale
path is ``LazyHDF5Users`` + ``LazyUserDataset`` (header-only eager read,
per-user on-demand IO, bounded LRU).  This tool measures that path at an
actual million-user pool:

1. stream-writes a 1e6-user hdf5 blob (reference create-hdf5 layout,
   group per user) without ever holding the pool in RAM;
2. opens it (the only eager cost: the 1e6-entry name/count header);
3. runs LR federated rounds through the REAL engine sampling K users a
   round from the full pool;
and reports wall times, file size, and host peak-RSS at each stage.  The
claim being evidenced: pool size costs disk and a header, not RAM —
round cost depends on K, not on pool size.

Usage: python tools/million_user_probe.py [pool_size] > million_user.json
CPU-only by design (the measured quantity is host IO/memory, not chip
math); run under the virtual-mesh env like the tests.
"""

from __future__ import annotations

import json
import os
import resource
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _rss_mb() -> float:
    """CURRENT resident set (VmRSS), not the lifetime peak — per-stage
    attribution needs the level at the stage boundary."""
    with open("/proc/self/status") as fh:
        for line in fh:
            if line.startswith("VmRSS:"):
                return round(int(line.split()[1]) / 1024.0, 1)
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                 / 1024.0, 1)


def _peak_rss_mb() -> float:
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                 / 1024.0, 1)


def main() -> int:
    pool = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    # a small-pool CONTROL measured with the identical method makes the
    # "round cost is independent of pool size" claim self-contained in
    # this one artifact
    control = min(2000, pool)
    spu, dim, classes = 10, 64, 10
    out = {"samples_per_user": spu, "input_dim": dim,
           "rss_mb_baseline": _rss_mb()}
    for label, n in (("control", control), ("pool", pool)):
        out[label] = _measure(n, spu, dim, classes)
    out["rss_mb_process_peak"] = _peak_rss_mb()
    print(json.dumps(out))
    return 0


def _measure(pool, spu, dim, classes):
    import h5py
    import numpy as np

    out = {"pool_users": pool}
    tmpdir = tempfile.mkdtemp(prefix="million_pool_")
    path = os.path.join(tmpdir, "pool.hdf5")
    try:
        # -- 1. stream-write: a shared separable template plus a cheap
        # per-user feature shift, never the whole pool in memory
        t0 = time.time()
        rng = np.random.default_rng(0)
        x_template = rng.normal(size=(spu, dim)).astype(np.float32)
        y_template = (np.arange(spu) % classes).astype(np.int64)
        x_template[:, 0] += (y_template * 2 - classes + 1) * 0.5
        # libver="latest": the 1.8 default's symbol-table groups degrade
        # badly past ~1e5 siblings; the new-format B-tree keeps creation
        # near-constant-rate at 1e6 groups
        with h5py.File(path, "w", libver="latest") as fh:
            fh.create_dataset("users", data=np.array(
                [f"u{i:07d}" for i in range(pool)], dtype="S"))
            fh.create_dataset("num_samples",
                              data=np.full(pool, spu, np.int64))
            grp = fh.create_group("user_data")
            lab = fh.create_group("user_data_label")
            for i in range(pool):
                u = f"u{i:07d}"
                # cheap per-user heterogeneity: a per-user feature shift
                # so FedAvg over K clients is not K copies of one client
                x = x_template + (i % 97) * 0.01
                grp.create_group(u).create_dataset("x", data=x)
                lab.create_dataset(u, data=y_template)
                if i and i % 100_000 == 0:
                    print(f"[million_probe] wrote {i} users "
                          f"({time.time() - t0:.0f}s)", file=sys.stderr)
        out["write_secs"] = round(time.time() - t0, 1)
        out["file_mb"] = round(os.path.getsize(path) / 1e6, 1)
        out["rss_mb_after_write"] = _rss_mb()

        # -- 2. open: the only eager cost is the name/count header
        from msrflute_tpu.data.dataset import LazyUserDataset
        from msrflute_tpu.data.user_blob import LazyHDF5Users
        t0 = time.time()
        users = LazyHDF5Users(path)
        out["open_secs"] = round(time.time() - t0, 2)
        out["num_users_seen"] = len(users.user_list)
        out["rss_mb_after_open"] = _rss_mb()

        # -- 3. federated rounds sampling K from the full pool (warmed,
        # so the number excludes the one-off XLA compile)
        from msrflute_tpu.config import FLUTEConfig
        from msrflute_tpu.engine import OptimizationServer
        from msrflute_tpu.models import make_task
        from msrflute_tpu.parallel import make_mesh
        K, rounds = 100, 8
        cfg = FLUTEConfig.from_dict({
            "model_config": {"model_type": "LR", "num_classes": classes,
                             "input_dim": dim},
            "strategy": "fedavg",
            "server_config": {
                "max_iteration": rounds,
                "num_clients_per_iteration": K,
                "initial_lr_client": 0.1,
                "optimizer_config": {"type": "sgd", "lr": 1.0},
                "val_freq": 100, "initial_val": False,
                "data_config": {"val": {"batch_size": 64}},
            },
            "client_config": {
                "optimizer_config": {"type": "sgd", "lr": 0.1},
                "data_config": {"train": {"batch_size": 10}},
            },
        })
        task = make_task(cfg.model_config)
        data = LazyUserDataset(users, cache_users=256)
        with tempfile.TemporaryDirectory() as mdir:
            server = OptimizationServer(task, cfg, data, val_dataset=None,
                                        model_dir=mdir, mesh=make_mesh(),
                                        seed=0)
            # warmup: compile + first rounds outside the timed window
            # (the bench_protocol pattern — extend max_iteration, train
            # again; the jitted round program is reused)
            t0 = time.time()
            server.train()
            out["warmup_rounds_secs"] = round(time.time() - t0, 2)
            server.config.server_config.max_iteration += rounds
            t0 = time.time()
            server.train()
            total = time.time() - t0
        out["rounds_timed"] = rounds
        out["clients_per_round"] = K
        out["secs_per_round"] = round(total / rounds, 3)
        out["rss_mb_after_rounds"] = _rss_mb()
    finally:
        try:
            os.remove(path)
            os.rmdir(tmpdir)
        except OSError:
            pass
    return out


if __name__ == "__main__":
    sys.exit(main())
