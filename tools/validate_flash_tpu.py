"""On-chip flash-attention validation: compiled kernels vs dense math.

Covers what the CPU suite cannot (real mosaic lowering of the
[B,H,S,D]-layout kernels and the lane-broadcast stat streams): forward,
all three gradients, causal + full, odd lengths (padding), and the lse
cotangent with global-position offsets.  Prints FLASH_TPU_OK on success.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def main() -> int:
    assert jax.default_backend() == "tpu", jax.default_backend()
    from msrflute_tpu.ops.pallas_attention import (_dense_lse,
                                                   flash_attention,
                                                   flash_attention_lse)

    B, L, H, D = 2, 513, 4, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)

    def dense(q, k, v, causal):
        return _dense_lse(q, k, v, 0, 0, causal)[0]

    ok = True
    for causal in (False, True):
        o = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal, force_flash=True))(q, k, v)
        err = float(jnp.max(jnp.abs(o - dense(q, k, v, causal))))
        print(("causal" if causal else "full  "), "fwd max err:", err)
        ok &= err < 1e-2
        gf = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal,
                                    force_flash=True) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        gd = jax.grad(
            lambda q, k, v: jnp.sum(dense(q, k, v, causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        errs = [float(jnp.max(jnp.abs(a - b))) for a, b in zip(gf, gd)]
        print("   bwd max errs dq/dk/dv:", errs)
        ok &= all(e < 1e-1 for e in errs)

    # lse cotangent with offsets (the ring-attention configuration)
    q2 = jnp.asarray(rng.normal(size=(1, 128, 2, 64)), jnp.float32)
    k2 = jnp.asarray(rng.normal(size=(1, 256, 2, 64)), jnp.float32)
    v2 = jnp.asarray(rng.normal(size=(1, 256, 2, 64)), jnp.float32)

    def obj(flash):
        def f(q, k, v):
            if flash:
                out, lse = flash_attention_lse(q, k, v, causal=True,
                                               q_offset=256, k_offset=64,
                                               force_flash=True)
            else:
                out, lse = _dense_lse(q, k, v, 256, 64, True)
            return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))
        return f

    gk = jax.jit(jax.grad(obj(True), argnums=(0, 1, 2)))(q2, k2, v2)
    gd = jax.grad(obj(False), argnums=(0, 1, 2))(q2, k2, v2)
    errs = [float(jnp.max(jnp.abs(a - b))) for a, b in zip(gk, gd)]
    print("lse-cotangent bwd max errs:", errs)
    ok &= all(e < 1e-1 for e in errs)

    print("FLASH_TPU_OK" if ok else "FLASH_TPU_MISMATCH")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
