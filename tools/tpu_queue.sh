#!/bin/bash
# Serialized TPU measurement queue.  The chip sits behind a single-client
# tunnel that WEDGES if a claiming process is killed — so: one job at a
# time, no kill timeouts, wait for recovery by polling with a real matmul.
cd /root/repo
log() { echo "[tpu_queue $(date +%H:%M:%S)] $*"; }

log "waiting for chip..."
tries=0
until python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
jax.block_until_ready(jnp.ones((128, 128)) @ jnp.ones((128, 128)))
EOF
do
  tries=$((tries+1)); log "probe $tries failed; sleeping 120s"; sleep 120
done
log "chip up"

log "1/5 flash on-chip validation"
python tools/validate_flash_tpu.py > tpu_flash_validation.log 2>&1
log "rc=$?"

log "2/5 pallas kernel tests on chip"
python -m pytest tests/test_pallas_kernels.py tests/test_pallas_attention.py \
  -q -p no:cacheprovider --noconftest > tpu_pallas_tests.log 2>&1
log "rc=$?"

log "3/5 longctx bench"
BENCH_PROTOCOLS=longctx_ringlm python bench.py > bench_longctx.json 2> bench_longctx.err
log "rc=$?"

log "4/5 profile cnn_femnist"
python tools/profile_round.py --protocol cnn_femnist --chunks 3 \
  > profile_cnn.json 2> profile_cnn.err
log "rc=$?"

log "5/5 scale probe"
BENCH_SCALE_PROBE=1 BENCH_PROTOCOLS=cnn_femnist python bench.py \
  > bench_scale.json 2> bench_scale.err
log "rc=$?"
log "queue done"
