#!/bin/bash
# Eval-cost decomposition on chip (VERDICT r4 weak #3 tail): bench.py's
# secs_eval (~0.07 s) exceeds a train round for the small protocols; this
# splits it into staged-grid size, device program time, and host overhead
# so the absolute is explained (expected: the single-client tunnel's
# dispatch floor, not eval compute).
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 1800 \
  python tools/profile_round.py --protocol lr_mnist --chunks 2 \
  > PROFILE_EVAL_LR_TPU.json 2> profile_eval_tpu.log
rc=$?
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 1800 \
  python tools/profile_round.py --protocol cnn_femnist --chunks 2 \
  > PROFILE_EVAL_CNN_TPU.json 2>> profile_eval_tpu.log
rc2=$?
bash tools/commit_tpu_artifacts.sh || true
[ "$rc" -eq 0 ] && [ "$rc2" -eq 0 ]
