#!/bin/bash
BENCH_DEADLINE_SECS=2400 BENCH_TPU_WAIT_SECS=60 \
  BENCH_PROTOCOLS=rnn_fedshakespeare \
  python bench.py > bench_tpu_rnn.json 2> bench_tpu_rnn.err
bash tools/commit_tpu_artifacts.sh || true
