#!/bin/bash
python tools/validate_flash_tpu.py > tpu_flash_validation.log 2>&1
rc=$?
bash tools/commit_tpu_artifacts.sh || true
exit $rc
