#!/bin/bash
BENCH_DEADLINE_SECS=7200 BENCH_TPU_WAIT_SECS=60 BENCH_SCALE_PROBE=1 BENCH_PROTOCOLS=cnn_femnist \
  python bench.py > bench_scale.json 2> bench_scale.err
bash tools/commit_tpu_artifacts.sh || true
