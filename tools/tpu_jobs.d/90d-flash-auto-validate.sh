#!/bin/bash
# Auto-policy proof (VERDICT r4 next #3 tail): after job 92's sweep and
# the FLASH_AUTO_MIN_LEN recalibration, show the ringlm "auto" select
# picking the measured-faster branch on BOTH sides of the crossover.
# Exit 1 (and a committed JSON showing the mismatch) if the shipped
# constant disagrees with a fresh measurement.
ATTEMPTS=/root/repo/.scratch/flash_auto_attempts
n=$(cat "$ATTEMPTS" 2>/dev/null || echo 0)
rearm() {
  if [ "$n" -ge 3 ]; then
    echo "[98-flash-auto] giving up after $n re-arms" >&2
    exit 1
  fi
  echo $((n + 1)) > "$ATTEMPTS"
  ( sleep 600; rm -f /root/repo/tools/tpu_jobs.d/90d-flash-auto-validate.sh.done ) \
    >/dev/null 2>&1 &
  disown
  exit 1
}
# -s: job 92's stdout redirect creates the file at launch, so a timed-out
# sweep leaves it empty — that is a re-arm, not a run
if [ ! -s /root/repo/flash_crossover.json ]; then
  echo "[98-flash-auto] no usable sweep artifact yet; re-arming" >&2
  rearm
fi
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 2400 \
  python tools/validate_flash_auto.py > FLASH_AUTO_VALIDATION.json 2> flash_auto_validation.err
rc=$?
bash tools/commit_tpu_artifacts.sh || true
if [ "$rc" -eq 2 ]; then
  echo "[98-flash-auto] sweep artifact unusable (rc 2); re-arming" >&2
  rearm
fi
exit $rc
