#!/bin/bash
# On-chip validation of the round-4 net-new strategies: secure
# aggregation (int32 modular tensordot/psum, fori_loop pairwise masks)
# and error-feedback quantization (host payload path + jitted EF step).
# Their CPU tests pass; this proves the TPU lowering of the integer
# group arithmetic on silicon.
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 3000 \
  python -m pytest tests/test_secure_agg.py tests/test_ef_quant.py \
  -q -p no:cacheprovider --noconftest > tpu_secagg_ef_tests.log 2>&1
rc=$?
bash tools/commit_tpu_artifacts.sh || true
exit $rc
