#!/bin/bash
# resnet wedged the tunnel mid-compile on the first attempt this round;
# run it AFTER lr+rnn so a recurrence cannot cost their artifacts.
# generous stall budget: a cold server-side resnet compile may be slow.
# Runs late so every per-protocol/validation artifact lands first; a
# wedge here can still strand the tunnel for the later all-in-one bench
# (80-), which is why that one is last and re-measures everything.
BENCH_DEADLINE_SECS=3600 BENCH_TPU_WAIT_SECS=60 \
  BENCH_PROTOCOL_STALL_SECS=2400 \
  BENCH_PROTOCOLS=resnet_fedcifar100 \
  python bench.py > bench_tpu_resnet.json 2> bench_tpu_resnet.err
bash tools/commit_tpu_artifacts.sh || true
