#!/bin/bash
# resnet wedged the tunnel mid-compile on the first attempt this round;
# run it AFTER lr+rnn so a recurrence cannot cost their artifacts.
BENCH_DEADLINE_SECS=2400 BENCH_TPU_WAIT_SECS=60 \
  BENCH_PROTOCOLS=resnet_fedcifar100 \
  python bench.py > bench_tpu_resnet.json 2> bench_tpu_resnet.err
bash tools/commit_tpu_artifacts.sh || true
