#!/bin/bash
BENCH_DEADLINE_SECS=3600 BENCH_TPU_WAIT_SECS=60 BENCH_PROTOCOLS=mlm_bert,varlen_bucketing \
  python bench.py > bench_bert_varlen.json 2> bench_bert_varlen.err
bash tools/commit_tpu_artifacts.sh || true
