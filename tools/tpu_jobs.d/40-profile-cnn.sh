#!/bin/bash
python tools/profile_round.py --protocol cnn_femnist --chunks 3 \
  > profile_cnn.json 2> profile_cnn.err
bash tools/commit_tpu_artifacts.sh || true
