#!/bin/bash
# Isolation B: compile ONLY the quant kernel (the first test in the
# twice-failed pallas job), tightly bounded.  rc=124 = its compile hangs
# the backend; an error in the log = a real Mosaic lowering bug to fix.
timeout -s TERM -k 60 600 python - > tpu_quant_kernel_probe.log 2>&1 <<'PYEOF'
import sys, os
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
from msrflute_tpu.ops.pallas_kernels import quant_bin_sparsify
g = jnp.asarray(np.random.default_rng(0).normal(size=(5000,)), jnp.float32)
out = quant_bin_sparsify(g, jnp.min(g), jnp.max(g),
                         jnp.quantile(jnp.abs(g), 0.5), n_bins=16,
                         interpret=False)
jax.block_until_ready(out)
print("QUANT_KERNEL_TPU_OK", np.asarray(out)[:4])
PYEOF
rc=$?
echo "probe rc=$rc" >> tpu_quant_kernel_probe.log
bash tools/commit_tpu_artifacts.sh || true
exit $rc
