#!/bin/bash
# Long-horizon cross-framework accuracy (VERDICT r4 next #5), our side ON
# CHIP: 300 sampled rounds of the CNN protocol over the 3400-user hard
# corpus (the reference side ran on host torch; tools/parity/longrun.py
# --phase ref).  Requires ref_rounds.json in the scratch — skip (rc 0,
# no .done removal needed) if the ref phase hasn't landed yet.
SCRATCH=/root/repo/.scratch/parity_longrun
# the ref phase runs ~30 min on the host; this is the LAST queue job, so
# a bounded wait holds nothing else up.  Exiting early would burn the
# job's one run (.done) with nothing re-arming it.
waited=0
while [ ! -f "$SCRATCH/ref_rounds.json" ] && [ "$waited" -lt 5400 ]; do
  sleep 60; waited=$((waited + 60))
done
if [ ! -f "$SCRATCH/ref_rounds.json" ]; then
  echo "[96-longrun] ref phase never landed after ${waited}s" >&2
  exit 1
fi
timeout -s TERM -k 60 3000 \
  python tools/parity/longrun.py --phase tpu --backend ambient \
  --scratch "$SCRATCH" > parity_longrun.log 2>&1
rc=$?
if [ "$rc" -eq 0 ]; then
  env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python tools/parity/longrun.py --phase compare --scratch "$SCRATCH" \
    >> parity_longrun.log 2>&1
  rc=$?
fi
bash tools/commit_tpu_artifacts.sh || true
exit $rc
