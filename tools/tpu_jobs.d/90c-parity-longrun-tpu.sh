#!/bin/bash
# Long-horizon cross-framework accuracy (VERDICT r4 next #5), our side ON
# CHIP: 300 sampled rounds of the CNN protocol over the 3400-user hard
# corpus (the reference side ran on host torch; tools/parity/longrun.py
# --phase ref).  The trainer budget is passed IN-TOOL
# (--tpu-timeout-secs): a shell `timeout` here would kill only the
# orchestrator and orphan the e2e_trainer child HOLDING the single-client
# tunnel claim (docs/RUNBOOK.md failure mode 4).
SCRATCH=/root/repo/.scratch/parity_longrun
# the ref phase runs ~30 min on the host; this is the LAST queue job, so
# a bounded wait holds nothing else up.  If it expires, RE-ARM: the
# runner stamps .done for any exit code, so a detached sleeper removes
# the stamp and the runner retries on a later pass.
waited=0
while [ ! -f "$SCRATCH/ref_rounds.json" ] && [ "$waited" -lt 5400 ]; do
  sleep 60; waited=$((waited + 60))
done
if [ ! -f "$SCRATCH/ref_rounds.json" ]; then
  echo "[96-longrun] ref phase not landed after ${waited}s; re-arming" >&2
  ( sleep 300; rm -f "/root/repo/tools/tpu_jobs.d/90c-parity-longrun-tpu.sh.done" ) \
    >/dev/null 2>&1 &
  disown
  exit 1
fi
python tools/parity/longrun.py --phase tpu --backend ambient \
  --tpu-timeout-secs 2700 \
  --scratch "$SCRATCH" > parity_longrun.log 2>&1
rc=$?
if [ "$rc" -eq 0 ]; then
  env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python tools/parity/longrun.py --phase compare --scratch "$SCRATCH" \
    >> parity_longrun.log 2>&1
  rc=$?
fi
bash tools/commit_tpu_artifacts.sh || true
exit $rc
