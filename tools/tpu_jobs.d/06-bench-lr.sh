#!/bin/bash
# Per-protocol artifact jobs (06-09): each lands its own committed
# backend:"tpu" capture, so a mid-queue tunnel wedge costs at most one
# protocol (plus the 20-min stall budget), never the whole bench.
BENCH_DEADLINE_SECS=2400 BENCH_TPU_WAIT_SECS=60 \
  BENCH_PROTOCOLS=lr_mnist \
  python bench.py > bench_tpu_lr.json 2> bench_tpu_lr.err
bash tools/commit_tpu_artifacts.sh || true
