#!/bin/bash
# Last queue job: commit whatever on-chip evidence the queue produced, so
# raw artifacts are in history even if the round ends while unattended.
# Single source of truth for the artifact list + per-pathspec add:
exec bash /root/repo/tools/commit_tpu_artifacts.sh
