#!/bin/bash
# Last queue job: commit whatever on-chip evidence the queue produced, so
# raw artifacts are in history even if the round ends while unattended.
cd /root/repo
git add -f BENCH_TPU_*.json bench_tpu_full.json bench_tpu_full.err \
  tpu_flash_validation.log tpu_pallas_tests.log profile_cnn.json \
  bench_scale.json bench_bert_varlen.json 2>/dev/null
git diff --cached --quiet && exit 0
git commit -m "Add raw on-chip measurement artifacts from the TPU queue

Serialized runs from tools/tpu_runner.sh the moment the tunnel cleared:
full bench (all protocols + bf16 + longctx + MFU), flash-attention
on-chip validation, Pallas kernel tests incl. the DP-noise PRNG
statistics, round profile, K-clients scale probe, bert+varlen bench."
