#!/bin/bash
# FIRST queue job: the headline protocol only — cheapest possible
# committed on-chip number, so even a minutes-long chip window yields
# the artifact the round is scored on.  The full bench runs next.
BENCH_DEADLINE_SECS=1800 BENCH_TPU_WAIT_SECS=60 \
  BENCH_PROTOCOLS=cnn_femnist \
  python bench.py > bench_tpu_headline.json 2> bench_tpu_headline.err
bash tools/commit_tpu_artifacts.sh || true
