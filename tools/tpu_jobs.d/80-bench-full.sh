#!/bin/bash
# Full on-chip bench: four protocols + bf16 + longctx + MFU.  Writes the
# timestamped BENCH_TPU_*.json raw artifact itself (bench.py main).
# The runner has no caller timeout, so raise the self-imposed deadline
# (default 25 min protects DRIVER runs) well above a full measurement.
BENCH_DEADLINE_SECS=7200 BENCH_TPU_WAIT_SECS=60 \
  python bench.py > bench_tpu_full.json 2> bench_tpu_full.err
bash tools/commit_tpu_artifacts.sh || true
