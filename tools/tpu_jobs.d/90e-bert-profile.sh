#!/bin/bash
# BERT MFU work (VERDICT r4 next #4): profile the mlm_bert round on chip
# (full head vs round-5's gathered MLM head), so the committed artifact
# pins where the time goes and what the head change bought.
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 2400 \
  python tools/profile_round.py --protocol mlm_bert --chunks 2 \
  > PROFILE_BERT_TPU.json 2> profile_bert_tpu.log
rc=$?
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 2400 \
  python tools/profile_round.py --protocol mlm_bert_gathered --chunks 2 \
  > PROFILE_BERT_GATHERED_TPU.json 2>> profile_bert_tpu.log
rc2=$?
bash tools/commit_tpu_artifacts.sh || true
[ "$rc" -eq 0 ] && [ "$rc2" -eq 0 ]
