#!/bin/bash
python -m pytest tests/test_pallas_kernels.py tests/test_pallas_attention.py \
  -q -p no:cacheprovider --noconftest > tpu_pallas_tests.log 2>&1
bash tools/commit_tpu_artifacts.sh || true
