#!/bin/bash
# TPU-gated kernel tests (flash attention mosaic lowering + on-core PRNG
# plumbing).  First-ever on-chip compiles are minutes each, so: a hard
# 50-min ceiling (SIGTERM; a wedged claim clears server-side once the
# process dies), and the persistent XLA compilation cache so a retry
# after a timeout starts hot instead of recompiling from zero.
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 3000 \
  python -m pytest tests/test_pallas_kernels.py tests/test_pallas_attention.py \
  -q -p no:cacheprovider --noconftest > tpu_pallas_tests.log 2>&1
rc=$?
bash tools/commit_tpu_artifacts.sh || true
exit $rc
