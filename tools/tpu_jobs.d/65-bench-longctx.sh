#!/bin/bash
BENCH_DEADLINE_SECS=2400 BENCH_TPU_WAIT_SECS=60 \
  BENCH_PROTOCOLS=longctx_ringlm BENCH_LONGCTX=1 \
  python bench.py > bench_tpu_longctx.json 2> bench_tpu_longctx.err
bash tools/commit_tpu_artifacts.sh || true
