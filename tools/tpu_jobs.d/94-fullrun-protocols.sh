#!/bin/bash
# Full-length end-to-end protocol runs (VERDICT r4 missing #1): the four
# reference protocols through the real CLI at published geometry —
# 100/1500/4000/1200 rounds, per-round latest checkpointing, eval at
# published cadence, full-size synthetic blobs.  Whole-run wall-clock vs
# the published FLUTE NCCL totals.  Also records a fused (TPU-best-
# practice) variant per protocol.  Per-protocol wedge budgets live inside
# the tool (published + headroom).
FULLRUN_FUSED=50 \
  python tools/fullrun_protocols.py > fullrun_tpu.log 2>&1
rc=$?
bash tools/commit_tpu_artifacts.sh || true
exit $rc
