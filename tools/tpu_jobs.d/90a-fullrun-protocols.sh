#!/bin/bash
# Full-length end-to-end protocol runs (VERDICT r4 missing #1): the four
# reference protocols through the real CLI at published geometry —
# 100/1500/4000/1200 rounds, per-round latest checkpointing, eval at
# published cadence, full-size synthetic blobs.  Whole-run wall-clock vs
# the published FLUTE NCCL totals.  Also records a fused (TPU-best-
# practice) variant per protocol.  Per-protocol wedge budgets live inside
# the tool (published + headroom), and the tool probes the chip between
# protocols (RUNBOOK failure mode 5).
#
# Rerun order: resnet+rnn FIRST — the 2026-08-01 first capture lost both
# to a wedge cascade while lr+cnn landed; if the window closes early the
# missing evidence lands first.  lr+cnn rerun after, with the faithful-
# mode fixes (batched stats fetch, checkpoint_async) in effect.
FULLRUN_FUSED=50 FULLRUN_PROTOCOLS=resnet_fedcifar100,rnn_fedshakespeare \
  python tools/fullrun_protocols.py > fullrun_tpu.log 2>&1
rc=$?
bash tools/commit_tpu_artifacts.sh || true
FULLRUN_FUSED=50 FULLRUN_PROTOCOLS=lr_mnist,cnn_femnist \
  python tools/fullrun_protocols.py >> fullrun_tpu.log 2>&1
rc2=$?
bash tools/commit_tpu_artifacts.sh || true
[ "$rc" -eq 0 ] && [ "$rc2" -eq 0 ]
