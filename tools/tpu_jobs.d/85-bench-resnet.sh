#!/bin/bash
# resnet wedged the tunnel mid-compile on the first attempt this round;
# run it AFTER lr+rnn so a recurrence cannot cost their artifacts.
# generous stall budget: a cold server-side resnet compile may be slow.
# Runs dead LAST (after the all-in-one 80- bench): the all-in-one
# measures resnet last internally and flushes every other protocol
# first, so a persistent wedge in this standalone retry strands only
# the retry — never the all-in-one artifact.
BENCH_DEADLINE_SECS=3600 BENCH_TPU_WAIT_SECS=60 \
  BENCH_PROTOCOL_STALL_SECS=2400 \
  BENCH_PROTOCOLS=resnet_fedcifar100 \
  python bench.py > bench_tpu_resnet.json 2> bench_tpu_resnet.err
bash tools/commit_tpu_artifacts.sh || true
