#!/bin/bash
# Real-data on-chip convergence (sklearn digits through the full engine).
# Runs late: the resnet family compile is the historical wedge suspect.
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 2400 \
  python tools/digits_tpu_convergence.py > digits_tpu.json 2> digits_tpu.err
rc=$?
bash tools/commit_tpu_artifacts.sh || true
exit $rc
