#!/bin/bash
# Isolation A: the attention-kernel tests alone.  Job 20's standalone
# flash validation passes on-chip, so these should too — a pass pins the
# 16-failure cascade on the kernels file that test-orders FIRST.
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 3000 \
  python -m pytest tests/test_pallas_attention.py \
  -q -p no:cacheprovider --noconftest > tpu_pallas_attention.log 2>&1
rc=$?
bash tools/commit_tpu_artifacts.sh || true
exit $rc
