#!/bin/bash
# Timing hygiene: hold the queue while a host test suite / heavy local
# job is running — host contention skews on-chip s/round (job 80's cnn
# read 4.46x contended vs 9.98x clean; docs/RUNBOOK.md).  Local work
# touches /root/repo/.scratch/host_busy while it runs; this job (re-armed
# by `rm 00-host-quiet.sh.done`) blocks the queue until it clears.
while [ -f /root/repo/.scratch/host_busy ]; do
  echo "[00-host-quiet] host busy; queue held"; sleep 30
done
exit 0
