#!/bin/bash
# Flash-vs-dense crossover sweep: lengths 1k..16k x kernel tile choices.
# Basis for the ringlm dense/flash auto-select and kernel tile defaults.
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 3000 \
  python tools/flash_crossover_sweep.py > flash_crossover.json 2> flash_crossover.err
rc=$?
bash tools/commit_tpu_artifacts.sh || true
exit $rc
