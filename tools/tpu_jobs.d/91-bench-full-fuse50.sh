#!/bin/bash
# Uncontended re-run of the all-in-one bench at the new fuse=50 default
# (job 80 ran at fuse=25 and shared the host with a pytest suite): one
# raw artifact carrying every protocol's best-practice number.
# 3600s cap (typical full run ~40 min): a start near the runner's
# 05:00 cutoff must not spill deep into the 06:00 driver bench window —
# the internal watchdog flushes whatever sections completed
BENCH_DEADLINE_SECS=3600 BENCH_TPU_WAIT_SECS=60 \
  python bench.py > bench_tpu_full_fuse50.json 2> bench_tpu_full_fuse50.err
bash tools/commit_tpu_artifacts.sh || true
