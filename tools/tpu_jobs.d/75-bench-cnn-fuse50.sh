#!/bin/bash
# Fusion-depth experiment: headline CNN protocol at rounds_per_step=50
# (one device dispatch per eval period) vs the default 25.  If dispatch
# latency over the tunnel is a visible share of s/round, this halves it.
BENCH_DEADLINE_SECS=2400 BENCH_TPU_WAIT_SECS=60 BENCH_FUSE=50 \
  BENCH_PROTOCOLS=cnn_femnist \
  python bench.py > bench_tpu_cnn_fuse50.json 2> bench_tpu_cnn_fuse50.err
bash tools/commit_tpu_artifacts.sh || true
