#!/bin/bash
# Per-dispatch overhead vs buffer count (faithful-fullrun diagnosis):
# the fuse=1 LR round dispatched in ~88 ms against a 0.14 ms trivial-op
# floor; this pins whether the cost is per-buffer so the stats-packing
# engine change rests on data.  Numbered 89 to run BEFORE the re-armed
# bench jobs: its result decides an engine refactor this round.
JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache \
  timeout -s TERM -k 60 1200 \
  python tools/dispatch_cost_probe.py > DISPATCH_COST_TPU.json 2> dispatch_cost.err
rc=$?
bash tools/commit_tpu_artifacts.sh || true
exit $rc
