#!/bin/bash
BENCH_DEADLINE_SECS=2400 BENCH_TPU_WAIT_SECS=60 \
  BENCH_PROTOCOLS=cnn_femnist,cnn_femnist_bf16 \
  python bench.py > bench_tpu_cnn_bf16.json 2> bench_tpu_cnn_bf16.err
bash tools/commit_tpu_artifacts.sh || true
