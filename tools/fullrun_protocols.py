"""Full-length end-to-end runs of the four reference benchmark protocols.

VERDICT r4, missing #1: every committed perf number so far is a per-round
microbench x extrapolation.  The reference's published numbers are WHOLE-RUN
wall-clocks — 100/1500/4000/1200 rounds including per-round
``latest_model`` checkpointing and the eval cadence
(``/root/reference/README.md:22-41``, ``core/server.py:530-558``).  This
tool closes that gap: it drives the REAL CLI (``e2e_trainer.py``) through
each protocol at the reference's published geometry (BASELINE.md):

    protocol             pool   K/round  batch  lr    rounds  eval freq
    lr_mnist             1000   10       10     0.03   100    20
    cnn_femnist          3400   10       20     0.1   1500    50
    resnet_fedcifar100    500   10       20     0.1   4000    50
    rnn_fedshakespeare    715   10        4     0.8   1200    50

on full-size synthetic blobs (the real datasets are unreachable — zero
egress; geometry and per-user sample counts match the real corpora), with
``rounds_per_step: 1`` so ``latest_model`` is written EVERY round exactly
like the reference, and eval at the published cadence on full-size
val/test blobs.  The measured quantity is the END-TO-END wall-clock of
the trainer process (startup + compile + all rounds + evals + checkpoint
I/O) — directly comparable to the published FLUTE NCCL totals
(1:35 / 8:22 / 1:42:01 / 21:50).

Each protocol runs as its own subprocess of the actual CLI; results land
in ``FULLRUN_TPU_<stamp>.json`` (or ``FULLRUN_CPU_*`` off-chip) with the
total wall-clock, the vs-published ratio, and the full val-accuracy curve
parsed from the run's ``metrics.jsonl``.

Env knobs:
    FULLRUN_PROTOCOLS=lr_mnist,cnn_femnist   subset selection
    FULLRUN_SMOKE=1                          tiny geometry (CI contract)
    FULLRUN_FUSED=N                          also run a rounds_per_step=N
                                             variant per protocol (the
                                             TPU-best-practice number;
                                             checkpoint cadence then
                                             follows the fuse boundary)
    FULLRUN_DATA_DIR=...                     blob cache (default
                                             .scratch/fullrun_data)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: published FLUTE NCCL whole-run wall-clocks, seconds
#: (reference README.md:38-41)
PUBLISHED_SECS = {
    "lr_mnist": 95.0,            # 00:01:35
    "cnn_femnist": 502.0,        # 00:08:22
    "resnet_fedcifar100": 6121.0,  # 01:42:01
    "rnn_fedshakespeare": 1310.0,  # 00:21:50
}

#: reference geometry (README.md:22-27; BASELINE.md table).  spu = samples
#: per user, matched to the real corpora's averages (MNIST 60k/1000,
#: federated EMNIST ~100/user, Fed-CIFAR-100 100/user, Shakespeare lines).
#: ``tscale``/``flip`` set corpus difficulty, ridge-probed offline so the
#: attached accuracy curves live in each protocol's published
#: neighborhood instead of saturating instantly (LR ~81%, CNN ~83%,
#: ResNet ~33%, Shakespeare next-char ~57% — README.md:38-41).  The
#: probes (0.79 / 0.78 / 0.30 at 6-8k samples) are small-sample LOWER
#: bounds — full-pool training lands somewhat higher (measured: LR
#: 0.847 at 60k samples/100 rounds, `FULLRUN_CPU_*.json`); the token
#: walk's flip rate caps next-char accuracy at ~1-flip.
PROTOCOLS = {
    "lr_mnist": dict(
        model={"model_type": "LR", "num_classes": 10, "input_dim": 784},
        pool=1000, spu=60, batch=10, lr=0.03, rounds=100, freq=20,
        shape=(784,), classes=10, val_users=100, val_spu=100, tscale=0.1),
    "cnn_femnist": dict(
        model={"model_type": "CNN", "num_classes": 62},
        pool=3400, spu=100, batch=20, lr=0.1, rounds=1500, freq=50,
        shape=(28, 28, 1), classes=62, val_users=340, val_spu=100,
        tscale=0.15),
    "resnet_fedcifar100": dict(
        model={"model_type": "RESNET", "num_classes": 100,
               "image_size": 32},
        pool=500, spu=100, batch=20, lr=0.1, rounds=4000, freq=50,
        shape=(32, 32, 3), classes=100, val_users=100, val_spu=100,
        tscale=0.08),
    "rnn_fedshakespeare": dict(
        model={"model_type": "RNN", "vocab_size": 90, "embed_dim": 8,
               "hidden_dim": 256, "seq_len": 80},
        pool=715, spu=50, batch=4, lr=0.8, rounds=1200, freq=50,
        shape=None, classes=90, val_users=100, val_spu=30, flip=0.45),
}

SMOKE_OVERRIDES = dict(pool=12, spu=10, rounds=4, freq=2,
                       val_users=4, val_spu=8)


def _shrink(spec: dict) -> dict:
    out = dict(spec)
    out.update(SMOKE_OVERRIDES)
    return out


# ----------------------------------------------------------------------
# synthetic full-size data, learnable (class-structured): accuracy curves
# must move, the compute per sample matches the real corpus shapes
# ----------------------------------------------------------------------
def _write_image_blob(path, pool, spu, shape, classes, seed, tscale):
    import h5py
    dim = int(np.prod(shape))
    rng = np.random.default_rng(seed)
    # ONE class template bank for every split (fixed seed, independent of
    # the per-split sample seed): train and val must share the label rule
    # or val accuracy measures an unrelated function and sits at chance
    templates = np.random.default_rng(12345).normal(
        size=(classes, dim)).astype(np.float32) * tscale
    with h5py.File(path, "w") as fh:
        users_grp = fh.create_group("user_data")
        names, counts = [], []
        for u in range(pool):
            y = rng.integers(0, classes, size=spu)
            x = (rng.normal(size=(spu, dim)).astype(np.float32)
                 + templates[y])
            g = users_grp.create_group(f"u{u:05d}")
            g.create_dataset("x", data=x.reshape((spu,) + shape))
            g.create_dataset("y", data=y.astype(np.int64))
            names.append(f"u{u:05d}")
            counts.append(spu)
        fh.create_dataset(
            "users", data=np.asarray(names, dtype=h5py.string_dtype()))
        fh.create_dataset("num_samples", data=np.asarray(counts))


def _write_token_blob(path, pool, spu, seq_len, vocab, seed, flip):
    import h5py
    rng = np.random.default_rng(seed)
    # learnable next-char rule: a FIXED random walk over the vocab (seed
    # independent of the split, same reason as the image templates) with
    # per-split sample noise, like the parity harness's synthetic
    # shakespeare; the flip rate caps next-char accuracy at ~1-flip
    step = np.random.default_rng(54321).integers(1, 7, size=vocab)
    with h5py.File(path, "w") as fh:
        users_grp = fh.create_group("user_data")
        names, counts = [], []
        for u in range(pool):
            start = rng.integers(1, vocab, size=(spu, 1))
            x = np.empty((spu, seq_len), np.int64)
            x[:, :1] = start
            for t in range(1, seq_len):
                nxt = (x[:, t - 1] + step[x[:, t - 1] % vocab]) % vocab
                flipped = rng.random(spu) < flip
                nxt = np.where(flipped, rng.integers(1, vocab, size=spu),
                               nxt)
                x[:, t] = np.maximum(nxt, 1)
            g = users_grp.create_group(f"u{u:05d}")
            g.create_dataset("x", data=x)
            names.append(f"u{u:05d}")
            counts.append(spu)
        fh.create_dataset(
            "users", data=np.asarray(names, dtype=h5py.string_dtype()))
        fh.create_dataset("num_samples", data=np.asarray(counts))


def _ensure_data(name: str, spec: dict, data_dir: str) -> dict:
    os.makedirs(data_dir, exist_ok=True)
    paths = {}
    for split, (pool, spu) in {
            "train": (spec["pool"], spec["spu"]),
            "val": (spec["val_users"], spec["val_spu"]),
            "test": (spec["val_users"], spec["val_spu"])}.items():
        # v3: shared-template corpus (split-independent label rule) at
        # ridge-probed difficulty; the version tag invalidates caches
        # from earlier generators
        hardness = spec.get("tscale", spec.get("flip"))
        fname = f"{name}_{split}_{pool}x{spu}_h{hardness}_v3.hdf5"
        fpath = os.path.join(data_dir, fname)
        # prune superseded generations of this split (a difficulty retune
        # or generator bump renames the cache; the orphans are GB-class)
        import glob as _glob
        for old in _glob.glob(os.path.join(data_dir,
                                           f"{name}_{split}_*.hdf5")):
            if os.path.basename(old) != fname:
                os.remove(old)
        if not os.path.exists(fpath):
            seed = {"train": 0, "val": 1, "test": 2}[split]
            if spec["shape"] is None:
                _write_token_blob(fpath, pool, spu,
                                  spec["model"]["seq_len"],
                                  spec["model"]["vocab_size"], seed,
                                  spec["flip"])
            else:
                _write_image_blob(fpath, pool, spu, spec["shape"],
                                  spec["classes"], seed, spec["tscale"])
        paths[split] = fname
    return paths


# ----------------------------------------------------------------------
def _config(name: str, spec: dict, paths: dict, fuse: int,
            on_tpu: bool) -> dict:
    """The six-section FLUTE config for one protocol run.

    ``rounds_per_step: 1`` (the faithful mode) makes the housekeeping
    tail — including the ``latest_model`` save — run EVERY round, the
    reference's cadence (``core/server.py:530``)."""
    return {
        "model_config": spec["model"],
        "strategy": "fedavg",
        "server_config": {
            "max_iteration": spec["rounds"],
            "num_clients_per_iteration": 10,
            "initial_lr_client": spec["lr"],
            "optimizer_config": {"type": "sgd", "lr": 1.0},
            "val_freq": spec["freq"], "rec_freq": spec["freq"],
            "initial_val": False, "initial_rec": False,
            "best_model_criterion": "acc",
            "rounds_per_step": fuse,
            # per-round latest saves overlap the next round's compute
            # (same durability contract as orbax async: a crash can lose
            # only the in-flight save) — without this the faithful fuse=1
            # mode pays a synchronous full-state device->host fetch every
            # round, the dominant cost on a remote-attached chip
            "checkpoint_async": True,
            # warm repeat compiles across protocols/runs
            "compilation_cache_dir": ".jax_cache",
            "data_config": {
                "val": {"batch_size": 256, "val_data": paths["val"]},
                "test": {"batch_size": 256, "test_data": paths["test"]},
            },
        },
        "client_config": {
            "optimizer_config": {"type": "sgd", "lr": spec["lr"]},
            "data_config": {"train": {
                "batch_size": spec["batch"],
                "list_of_train_data": paths["train"],
                # TPU-native data path (bit-identical to host packing,
                # tests/test_device_pool.py): the flat sample pool lives
                # in HBM, per-round only [K,S,B] indices cross the host
                "device_resident": bool(on_tpu),
            }},
        },
    }


def _parse_metrics(out_dir: str):
    """Val-acc curve + timing stats from the run's metrics.jsonl."""
    curve, timing = [], {}
    path = os.path.join(out_dir, "log", "metrics.jsonl")
    if not os.path.exists(path):
        return curve, timing
    with open(path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except Exception:
                continue
            if rec.get("name") == "Val acc":
                curve.append([rec.get("step"), round(float(rec["value"]), 4)])
            if str(rec.get("name", "")).startswith("secsPerRound"):
                timing[rec["name"]] = round(float(rec["value"]), 4)
    return curve, timing


#: set once _wait_chip exhausts a full budget — later protocols fail fast
_CHIP_GAVE_UP = False


def _wait_chip(on_tpu: bool, budget_secs: float = 1800.0) -> bool:
    """Block until the chip answers a real matmul, or the budget expires.

    Observed live (FULLRUN_TPU 2026-08-01): one trainer dying mid-claim
    wedges the single-client tunnel, and every LATER protocol in the same
    job then hangs at its first device op until the axon client's ~25 min
    internal deadline kills it — a cascade that burned three protocol
    slots.  The queue runner probes between JOBS; this is the same probe
    between PROTOCOLS."""
    global _CHIP_GAVE_UP
    if not on_tpu:
        return True
    if _CHIP_GAVE_UP:
        return False  # one exhausted budget is enough; don't re-wait per protocol
    deadline = time.time() + budget_secs
    probe = ("import jax, jax.numpy as jnp\n"
             "assert jax.default_backend() == 'tpu'\n"
             "jax.block_until_ready(jnp.ones((128,128)) @ jnp.ones((128,128)))\n")
    # graceful timeout via coreutils (TERM, then KILL only after a 30s
    # grace): subprocess.run(timeout=...) SIGKILLs on expiry, and a
    # SIGKILLed claimant is exactly what wedges the tunnel (the runner's
    # own probe uses this same shell form)
    cmd = ["timeout", "-s", "TERM", "-k", "30", "120",
           sys.executable, "-c", probe]
    instant_failures = 0
    while time.time() < deadline:
        tic = time.time()
        r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
        if r.returncode == 0:
            return True
        took = time.time() - tic
        print(f"[fullrun] chip probe rc={r.returncode} after {took:.0f}s; "
              f"stderr: {(r.stderr or '')[-300:]}", file=sys.stderr)
        if r.returncode != 124 and took < 10:
            # instant non-timeout failure = misconfiguration (bad env,
            # missing plugin), not a wedged claim — sleeping can't fix it
            instant_failures += 1
            if instant_failures >= 3:
                break
        if time.time() + 180 >= deadline:
            break
        print("[fullrun] waiting 180s for the claim to age out",
              file=sys.stderr)
        time.sleep(180)
    _CHIP_GAVE_UP = True
    return False


def run_protocol(name: str, spec: dict, data_dir: str, out_root: str,
                 fuse: int, on_tpu: bool) -> dict:
    paths = _ensure_data(name, spec, data_dir)
    if not _wait_chip(on_tpu):
        return {"rounds": spec["rounds"], "total_secs": None,
                "published_secs": PUBLISHED_SECS.get(name),
                "vs_published": None, "rounds_per_step": fuse,
                "returncode": "chip-unreachable", "timing": {},
                "val_acc_curve": []}
    tag = f"{name}_fuse{fuse}"
    out_dir = os.path.join(out_root, tag)
    # a reused output dir APPENDS to metrics.jsonl and the parsed curve
    # then interleaves runs — each invocation starts clean
    import shutil
    shutil.rmtree(out_dir, ignore_errors=True)
    cfg_path = os.path.join(out_root, f"{tag}.yaml")
    with open(cfg_path, "w") as fh:
        yaml.safe_dump(_config(name, spec, paths, fuse, on_tpu), fh)
    cmd = [sys.executable, os.path.join(REPO, "e2e_trainer.py"),
           "-config", cfg_path, "-dataPath", data_dir,
           "-outputPath", out_dir, "-task", name]
    # wedge protection: the run must finish WELL under the published
    # wall-clock for the number to mean anything, so published + compile
    # headroom is a generous budget; killing a wedged claimant lets the
    # tunnel server age the claim out (docs/RUNBOOK.md)
    budget = (PUBLISHED_SECS.get(name) or 600.0) + 600.0
    if os.environ.get("FULLRUN_SMOKE"):
        budget = 300.0
    tic = time.time()
    try:
        proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                              text=True, timeout=budget)
    except subprocess.TimeoutExpired as exc:
        total = time.time() - tic
        curve, timing = _parse_metrics(out_dir)
        return {
            "rounds": spec["rounds"], "total_secs": round(total, 1),
            "published_secs": PUBLISHED_SECS.get(name),
            "vs_published": None, "rounds_per_step": fuse,
            "returncode": "timeout",
            "timing": timing, "val_acc_curve": curve,
            "stderr_tail": (exc.stderr or b"")[-2000:].decode(
                "utf-8", "replace") if isinstance(exc.stderr, bytes)
            else str(exc.stderr or "")[-2000:],
        }
    total = time.time() - tic
    curve, timing = _parse_metrics(out_dir)
    published = PUBLISHED_SECS.get(name)
    res = {
        "rounds": spec["rounds"],
        "total_secs": round(total, 1),
        "published_secs": published,
        "vs_published": (round(published / total, 2)
                         if published and proc.returncode == 0 else None),
        "rounds_per_step": fuse,
        "returncode": proc.returncode,
        "secs_per_round_incl_everything": round(total / spec["rounds"], 4),
        "timing": timing,
        "final_val_acc": curve[-1][1] if curve else None,
        "val_acc_curve": curve,
    }
    if proc.returncode != 0:
        res["stderr_tail"] = proc.stderr[-2000:]
    return res


def main() -> None:
    on_tpu = os.environ.get("JAX_PLATFORMS", "") not in ("cpu",) and \
        bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
    smoke = bool(os.environ.get("FULLRUN_SMOKE"))
    data_dir = os.environ.get(
        "FULLRUN_DATA_DIR",
        os.path.join(REPO, ".scratch",
                     "fullrun_data" + ("_smoke" if smoke else "")))
    out_root = os.path.join(REPO, ".scratch",
                            "fullrun_out" + ("_smoke" if smoke else ""))
    os.makedirs(out_root, exist_ok=True)
    keep = os.environ.get("FULLRUN_PROTOCOLS")
    names = [n for n in PROTOCOLS
             if keep is None or n in keep.split(",")]
    fused_extra = int(os.environ.get("FULLRUN_FUSED", 0) or 0)

    results = {}
    for name in names:
        spec = _shrink(PROTOCOLS[name]) if smoke else PROTOCOLS[name]
        print(f"[fullrun] {name}: generating data + running "
              f"{spec['rounds']} rounds (faithful, fuse=1)", file=sys.stderr)
        results[name] = run_protocol(name, spec, data_dir, out_root,
                                     fuse=1, on_tpu=on_tpu)
        print(f"[fullrun] {name}: {results[name]['total_secs']}s "
              f"(vs_published {results[name]['vs_published']})",
              file=sys.stderr)
        if fused_extra > 1:
            results[f"{name}_fused{fused_extra}"] = run_protocol(
                name, spec, data_dir, out_root, fuse=fused_extra,
                on_tpu=on_tpu)

    stamp = time.strftime("%Y%m%d_%H%M%S")
    payload = {
        "kind": "fullrun_protocols",
        "backend": "tpu" if on_tpu else "cpu",
        "smoke": smoke,
        "captured_at": stamp,
        "geometry": "reference README.md:22-27; per-round latest "
                    "checkpointing (core/server.py:530-558); eval at "
                    "published cadence; synthetic full-size blobs",
        "protocols": results,
    }
    prefix = "FULLRUN_TPU" if on_tpu else "FULLRUN_CPU"
    if smoke:
        prefix += "_SMOKE"
    out_path = os.path.join(REPO, f"{prefix}_{stamp}.json")
    with open(out_path, "w") as fh:
        json.dump(payload, fh, indent=1)
    print(json.dumps(payload))
    print(f"[fullrun] wrote {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
