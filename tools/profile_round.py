"""One-command round profiling — where does a federated round's time go?

The reference's answer is flag-gated cProfile dumps (``core/server.py:
327-331``, SURVEY §5.1); the TPU answer is this CLI: run one benchmark
protocol for a few fused chunks, split wall-clock into host packing vs
device execution, attach the compiled program's own cost analysis
(FLOPs/bytes from XLA), optionally capture a ``jax.profiler`` trace, and
print one JSON object.

Usage::

    python tools/profile_round.py --protocol cnn_femnist --chunks 3
    python tools/profile_round.py --protocol lr_mnist --trace /tmp/trace
    BENCH_BACKEND=cpu python tools/profile_round.py ...   # force backend

Run it the moment the chip answers: ``pack_share`` (host packing as a
fraction of the round) says whether to optimize kernels or the host path
first.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--protocol", default="cnn_femnist",
                    help="one of bench.py's protocols")
    ap.add_argument("--chunks", type=int, default=3,
                    help="timed fused-round chunks after warmup")
    ap.add_argument("--trace", default=None,
                    help="directory for a jax.profiler trace of one chunk")
    args = ap.parse_args(argv)

    import bench  # repo-root harness: backend probe + protocol table

    backend, reason = bench.select_backend()
    on_tpu = backend == "tpu"
    rng = np.random.default_rng(0)
    protocols = bench.build_protocols(on_tpu, rng, with_bf16=True)
    if args.protocol not in protocols:
        raise SystemExit(f"unknown protocol {args.protocol!r}; have "
                         f"{sorted(protocols)}")
    spec = protocols[args.protocol]
    cfg, dataset = spec["cfg"], spec["data"]()

    import jax
    from msrflute_tpu.data import pack_round_batches
    from msrflute_tpu.engine import OptimizationServer
    from msrflute_tpu.models import make_task
    from msrflute_tpu.parallel import make_mesh
    from msrflute_tpu.telemetry.timing import Stopwatch

    mesh = make_mesh()
    task = make_task(cfg.model_config)
    fuse = int(cfg.server_config.get("rounds_per_step", 1))
    out = {"protocol": args.protocol, "backend": backend,
           "backend_reason": reason, "rounds_per_step": fuse}

    with tempfile.TemporaryDirectory() as tmp:
        server = OptimizationServer(task, cfg, dataset, model_dir=tmp,
                                    mesh=mesh, seed=0)
        # ---- compile (first chunk) ----
        # telemetry.timing.Stopwatch everywhere below: the same clock
        # the server spans and bench.py use (one timing source of
        # truth); JSON field names unchanged
        with Stopwatch() as sw:
            server.config.server_config.max_iteration = fuse
            server.train()
            jax.block_until_ready(server.state.params)
        out["compile_plus_first_chunk_secs"] = round(sw.secs, 3)

        # ---- host packing cost, measured alone — with the SAME client
        # padding the server uses (pad_to_mesh), or the share is
        # understated exactly on the hardware this tool targets ----
        from msrflute_tpu.parallel.mesh import pad_to_mesh
        sampled = list(range(int(
            cfg.server_config.num_clients_per_iteration)))
        bs = int(cfg.client_config.data_config.train["batch_size"])
        pad_to = pad_to_mesh(len(sampled), mesh)
        pool_mode = server._pool_offsets is not None
        sw = Stopwatch().__enter__()
        for _ in range(5):
            if pool_mode:
                # device-resident pool: the server packs int32 indices,
                # not feature rows — measure what it actually pays
                from msrflute_tpu.data import pack_round_indices
                pack_round_indices(dataset, server._pool_offsets, sampled,
                                   bs, server.max_steps,
                                   rng=np.random.default_rng(0),
                                   pad_clients_to=pad_to)
            else:
                pack_round_batches(dataset, sampled, bs, server.max_steps,
                                   rng=np.random.default_rng(0),
                                   pad_clients_to=pad_to)
        sw.__exit__()
        pack_secs = sw.secs / 5
        out["pack_secs_per_round"] = round(pack_secs, 5)
        out["device_resident_pool"] = pool_mode

        # ---- optional trace chunk: profiler instrumentation inflates
        # wall time, so it is NOT counted into the steady-state stats.
        # Capture goes through the compat wrappers (telemetry's
        # profile_rounds path) so old jax degrades to a note, not a
        # crash ----
        if args.trace:
            from msrflute_tpu.utils.compat import (profiler_start_trace,
                                                   profiler_stop_trace)
            if profiler_start_trace(args.trace):
                server.config.server_config.max_iteration += fuse
                server.train()
                jax.block_until_ready(server.state.params)
                profiler_stop_trace()
                out["trace_dir"] = args.trace
            else:
                out["trace_error"] = "jax.profiler unavailable"

        # ---- timed chunks (the steady state) ----
        per_round = []
        for _ in range(max(args.chunks, 1)):
            server.config.server_config.max_iteration += fuse
            with Stopwatch() as sw:
                server.train()
                jax.block_until_ready(server.state.params)
            per_round.append(sw.secs / fuse)
        out["secs_per_round_p50"] = round(float(np.percentile(per_round, 50)), 5)
        out["secs_per_round_p90"] = round(float(np.percentile(per_round, 90)), 5)
        out["pack_share"] = round(pack_secs / max(np.median(per_round), 1e-9), 3)

        # ---- static per-op-type FLOP decomposition (chip-independent):
        # where the client grad step's FLOPs go — conv/dot (MXU) vs
        # elementwise/bookkeeping (VPU) — so the compute-bound argument
        # doesn't need the chip (utils/flops.py) ----
        one = bench._one_client_batch(dataset, bs, server.max_steps)
        try:
            from msrflute_tpu.utils.flops import flops_by_op

            def _grad_step(p):
                return jax.grad(lambda pp: task.loss(
                    pp, one, jax.random.PRNGKey(0), True)[0])(p)

            out["flops_by_op"] = flops_by_op(_grad_step,
                                             server.state.params)
        except Exception as exc:  # decomposition must not kill the tool
            out["flops_by_op_error"] = f"{type(exc).__name__}: {exc}"

        # ---- XLA's own cost + memory analysis of one client grad step
        # (the shared telemetry/xla.py helper — the same numbers the
        # live device-truth layer records, so this report can never
        # disagree with a scorecard) ----
        cost = bench.grad_step_cost(task, server.state.params, one)
        if cost is not None:
            from msrflute_tpu.telemetry.xla import mfu as mfu_of
            from msrflute_tpu.utils.compat import chip_peak_flops
            flops = float(cost.get("flops", 0.0))
            out["client_step_flops"] = flops
            out["client_step_bytes"] = float(cost.get("bytes_accessed",
                                                      0.0))
            if "hbm_bytes" in cost:
                out["client_step_hbm_bytes"] = cost["hbm_bytes"]
            out["round_model_flops"] = flops * server.max_steps * len(sampled)
            chip_kind, chip_peak = chip_peak_flops()
            value = mfu_of(out["round_model_flops"],
                           float(np.median(per_round)),
                           peak_flops=chip_peak)
            if value is not None:
                out["mfu_vs_chip_peak"] = {"chip": chip_kind,
                                           "mfu": round(value, 6)}
            if on_tpu:
                out["mfu_vs_bf16_peak"] = round(
                    mfu_of(out["round_model_flops"],
                           float(np.median(per_round)),
                           peak_flops=bench.V5E_BF16_PEAK_FLOPS) or 0.0, 5)
        else:
            # structured (not silently swallowed): name the helper that
            # declined so an operator knows WHICH layer has no analysis
            out["cost_analysis_error"] = (
                "telemetry.xla.aot_cost returned None — XLA cost "
                "analysis unavailable on this jax/backend")

        # ---- eval cost breakdown: bench.py's secs_eval is an absolute
        # (~0.07 s even for tiny protocols) larger than a train round;
        # split it into its parts so the absolute is explained, not just
        # amortized away by the eval cadence ----
        try:
            from msrflute_tpu.engine.evaluation import evaluate
            # the profiled server is built without a val split; use the
            # SAME val_ds bench.py times as secs_eval
            server.val_dataset = bench.make_val_ds(dataset, 8)
            server._eval_batches_cache.pop("val", None)
            with Stopwatch() as sw:
                staged = server._packed_eval_batches("val")
                # sync the staging transfers with an indexed scalar fetch
                # per leaf — block_until_ready is not a trustworthy fence
                # on the remote backend
                jax.device_get({k: v[(0,) * v.ndim]
                                for k, v in staged.items()})
            cold_pack = sw.secs
            first = next(iter(staged.values()))
            ev = {"split": "val",
                  "grid_steps_T": int(first.shape[0]),
                  "batch_B": int(first.shape[1]),
                  "grid_bytes": int(sum(int(np.prod(v.shape)) * v.dtype.itemsize
                                        for v in staged.values())),
                  "cold_pack_and_stage_secs": round(cold_pack, 5)}
            # device-only: the jitted scan+psum program on staged arrays.
            # Sync by fetching the (tiny) stat sums — block_until_ready is
            # not a trustworthy fence on the remote backend (see
            # flash_crossover.json history); evaluate() itself device_gets,
            # so this matches what the server's eval path actually pays
            # compile + first run, synced so the warm-up execution cannot
            # drain into the first timed sample
            jax.device_get(server._eval_fn(server.state.params, staged))
            times = []
            for _ in range(10):
                with Stopwatch() as sw:
                    jax.device_get(server._eval_fn(server.state.params,
                                                   staged))
                times.append(sw.secs)
            ev["device_secs_p50"] = round(float(np.percentile(times, 50)), 5)
            # full path as the server pays it each cadence hit: device_put
            # no-ops + device run + device_get + host metric finalize
            times = []
            for _ in range(10):
                with Stopwatch() as sw:
                    evaluate(task, server._eval_fn, server.state.params,
                             staged, mesh, server.engine.partition_mode)
                times.append(sw.secs)
            ev["full_eval_secs_p50"] = round(float(np.percentile(times, 50)), 5)
            ev["host_overhead_secs"] = round(
                ev["full_eval_secs_p50"] - ev["device_secs_p50"], 5)
            out["eval_breakdown"] = ev
        except Exception as exc:  # breakdown must not kill the tool
            out["eval_breakdown_error"] = f"{type(exc).__name__}: {exc}"

        # ---- per-round checkpoint cost: the faithful fullrun saves
        # ``latest`` every round (reference cadence); on a remote-attached
        # chip the full-state fetch is the suspected dominant cost.  Time
        # the synchronous save (fetch + serialize + write) and the
        # device->host fetch alone, so FULLRUN numbers decompose ----
        try:
            from msrflute_tpu.engine.checkpoint import LATEST, _payload
            state = server.state
            nbytes = sum(int(np.prod(x.shape)) * x.dtype.itemsize
                         for x in jax.tree.leaves(_payload(state))
                         if hasattr(x, "shape"))
            times_f, times_s = [], []
            for _ in range(5):
                with Stopwatch() as sw:
                    jax.device_get(_payload(state))
                times_f.append(sw.secs)
                with Stopwatch() as sw:
                    server.ckpt._write(os.path.join(
                        server.ckpt.model_dir, LATEST), state)
                times_s.append(sw.secs)
            out["checkpoint_cost"] = {
                "state_bytes": int(nbytes),
                "fetch_secs_p50": round(float(np.percentile(times_f, 50)), 5),
                "sync_save_secs_p50": round(float(np.percentile(times_s, 50)), 5),
                "device_to_host_mb_per_s": round(
                    nbytes / 1e6 / max(float(np.percentile(times_f, 50)),
                                       1e-9), 2),
            }
        except Exception as exc:
            out["checkpoint_cost_error"] = f"{type(exc).__name__}: {exc}"

    print(json.dumps(out))


if __name__ == "__main__":
    main()
